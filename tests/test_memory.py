"""Spill-tier tests (ref: RapidsDeviceMemoryStoreSuite,
RapidsHostMemoryStoreSuite, RapidsDiskStoreSuite, RapidsBufferCatalogSuite,
GpuSemaphoreSuite)."""

import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch, host_to_device, \
    device_to_host
from spark_rapids_tpu.memory import (
    PRIORITY_ACTIVE_INPUT, PRIORITY_DEFAULT, PRIORITY_SHUFFLE_OUTPUT,
    BufferCatalog, SpillableBatch, StorageTier, TpuSemaphore)
from spark_rapids_tpu.memory.native import (
    NativeSpillFile, PySpillFile, load, open_spill_file)


def make_batch(seed, n=64):
    rng = np.random.default_rng(seed)
    hb = HostBatch.from_pydict(
        [("a", dt.INT64), ("s", dt.STRING)],
        {"a": rng.integers(0, 1000, n).tolist(),
         "s": [f"row{seed}_{i}" for i in range(n)]})
    return host_to_device(hb)


class TestNativeSpillFile:
    def test_native_lib_compiles(self):
        assert load() is not None, "g++ native spill store must build"

    def test_write_read_free(self, tmp_path):
        f = open_spill_file(str(tmp_path))
        assert isinstance(f, NativeSpillFile)
        b1 = f.write(b"hello world")
        b2 = f.write(b"x" * 4096)
        assert f.read(b1) == b"hello world"
        assert f.read(b2) == b"x" * 4096
        assert f.allocated_bytes == 11 + 4096
        f.free(b1)
        assert f.allocated_bytes == 4096
        # Freed range is reused (first-fit): write something smaller.
        b3 = f.write(b"abc")
        assert f.read(b3) == b"abc"
        assert f.file_bytes == 11 + 4096   # no growth
        f.close()

    def test_python_fallback_equivalent(self, tmp_path):
        f = PySpillFile(str(tmp_path))
        b1 = f.write(b"data1")
        assert f.read(b1) == b"data1"
        f.free(b1)
        f.close()


class TestCatalogSpill:
    def test_device_to_host_spill_on_budget(self, tmp_path):
        b = make_batch(1)
        size = b.device_size_bytes()
        cat = BufferCatalog(device_budget_bytes=int(size * 2.5),
                            host_budget_bytes=1 << 30,
                            spill_dir=str(tmp_path))
        ids = [cat.add_batch(make_batch(i)) for i in range(3)]
        # Third add must have pushed the first (lowest id) to host.
        assert cat.tier_of(ids[0]) == StorageTier.HOST
        assert cat.tier_of(ids[2]) == StorageTier.DEVICE
        assert cat.metrics["spill_to_host"] >= 1
        # Re-acquire: comes back to device, bit-identical.
        restored = cat.acquire_batch(ids[0])
        assert cat.tier_of(ids[0]) == StorageTier.DEVICE
        orig = device_to_host(make_batch(0)).to_pylist()
        assert device_to_host(restored).to_pylist() == orig
        cat.close()

    def test_cascade_to_disk_and_restore(self, tmp_path):
        b = make_batch(0)
        size = b.device_size_bytes()
        cat = BufferCatalog(device_budget_bytes=int(size * 1.5),
                            host_budget_bytes=int(size * 1.5),
                            spill_dir=str(tmp_path))
        ids = [cat.add_batch(make_batch(i)) for i in range(4)]
        tiers = [cat.tier_of(i) for i in ids]
        assert StorageTier.DISK in tiers
        assert cat.metrics["spill_to_disk"] >= 1
        disk_id = ids[tiers.index(StorageTier.DISK)]
        seed = ids.index(disk_id)
        restored = cat.acquire_batch(disk_id)
        expect = device_to_host(make_batch(seed)).to_pylist()
        assert device_to_host(restored).to_pylist() == expect
        assert cat.metrics["restore_from_disk"] == 1
        cat.close()

    def test_priorities_shuffle_spills_first(self, tmp_path):
        b = make_batch(0)
        size = b.device_size_bytes()
        cat = BufferCatalog(device_budget_bytes=int(size * 2.5),
                            spill_dir=str(tmp_path))
        keep = cat.add_batch(make_batch(1), PRIORITY_DEFAULT)
        shuffle = cat.add_batch(make_batch(2), PRIORITY_SHUFFLE_OUTPUT)
        cat.add_batch(make_batch(3))   # forces one spill
        assert cat.tier_of(shuffle) == StorageTier.HOST
        assert cat.tier_of(keep) == StorageTier.DEVICE
        cat.close()

    def test_active_input_never_spills(self, tmp_path):
        b = make_batch(0)
        size = b.device_size_bytes()
        cat = BufferCatalog(device_budget_bytes=int(size * 1.5),
                            spill_dir=str(tmp_path))
        active = cat.add_batch(make_batch(1), PRIORITY_ACTIVE_INPUT)
        cat.add_batch(make_batch(2))
        cat.add_batch(make_batch(3))
        assert cat.tier_of(active) == StorageTier.DEVICE
        cat.close()

    def test_spillable_batch_handle(self, tmp_path):
        cat = BufferCatalog(spill_dir=str(tmp_path))
        sb = SpillableBatch(cat, make_batch(5))
        with sb as batch:
            assert int(batch.num_rows) == 64
        sb.close()
        cat.close()


class TestSemaphore:
    def test_limits_concurrency(self):
        sem = TpuSemaphore(2)
        active = []
        peak = []
        lock = threading.Lock()

        def task():
            with sem:
                with lock:
                    active.append(1)
                    peak.append(len(active))
                time.sleep(0.02)
                with lock:
                    active.pop()

        threads = [threading.Thread(target=task) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert max(peak) <= 2


class TestOomRetry:
    """OOM -> spill -> retry (DeviceMemoryEventHandler.scala:42-69
    analog, memory/oom.py): a RESOURCE_EXHAUSTED dispatch spills every
    spillable catalog buffer and re-runs the dispatch once."""

    def test_retry_after_spill(self, tmp_path):
        from spark_rapids_tpu.memory.oom import (retry_on_oom,
                                                 set_active_catalog)
        cat = BufferCatalog(device_budget_bytes=1 << 30,
                            spill_dir=str(tmp_path))
        bid = cat.add_batch(make_batch(1))
        cat.release(bid)
        set_active_catalog(cat)
        try:
            calls = []

            def flaky():
                calls.append(1)
                if len(calls) == 1:
                    raise RuntimeError(
                        "RESOURCE_EXHAUSTED: Out of memory allocating "
                        "12345 bytes")
                return "ok"

            assert retry_on_oom(flaky) == "ok"
            assert len(calls) == 2
            assert cat._entries[bid].tier == StorageTier.HOST
            assert cat.metrics.get("oom_spills") == 1
            # The spilled batch restores transparently.
            back = device_to_host(cat.acquire_batch(bid), ("a", "s"))
            assert back.num_rows == 64
        finally:
            set_active_catalog(None)

    def test_non_oom_propagates(self):
        from spark_rapids_tpu.memory.oom import retry_on_oom

        def bad():
            raise ValueError("boom")

        with pytest.raises(ValueError):
            retry_on_oom(bad)

    def test_oom_with_nothing_spillable_reraises(self, tmp_path):
        from spark_rapids_tpu.memory.oom import (retry_on_oom,
                                                 set_active_catalog)
        cat = BufferCatalog(device_budget_bytes=1 << 30,
                            spill_dir=str(tmp_path))
        set_active_catalog(cat)
        try:
            def oom():
                raise RuntimeError("RESOURCE_EXHAUSTED")

            with pytest.raises(RuntimeError):
                retry_on_oom(oom)
        finally:
            set_active_catalog(None)
