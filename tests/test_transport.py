"""Shuffle transport SPI (ISSUE 6): selection, shard wire format,
hostfile spool/manifest/rendezvous semantics, and the cross-process
demonstration — two independent worker processes map-write shards that
the parent reduce-fetches through the same SPI.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import (HostBatch, HostColumn,
                                            device_to_host, host_to_device)
from spark_rapids_tpu.memory.stores import (batch_to_shard_blob,
                                            shard_blob_to_batch)
from spark_rapids_tpu.parallel import transport as T
from spark_rapids_tpu.parallel.transport.base import ShardLostError
from spark_rapids_tpu.parallel.transport.hostfile import HostFileTransport
from spark_rapids_tpu.parallel.transport import rendezvous as RV


def _batch(keys, vals):
    hb = HostBatch(
        ("k", "v"),
        [HostColumn(dt.INT64, np.asarray(keys, np.int64),
                    np.ones(len(keys), bool)),
         HostColumn(dt.INT64, np.asarray(vals, np.int64),
                    np.ones(len(vals), bool))])
    return host_to_device(hb)


def _rows(batch):
    return device_to_host(batch).to_pylist()


# ---------------------------------------------------------------------------
# Selection
# ---------------------------------------------------------------------------

def test_transport_selection_conf_env_legacy(monkeypatch):
    monkeypatch.delenv("SRT_SHUFFLE_TRANSPORT", raising=False)
    assert T.transport_name(C.TpuConf()) == "inprocess"
    assert T.transport_name(C.TpuConf(
        {C.SHUFFLE_TRANSPORT.key: "hostfile"})) == "hostfile"
    # Legacy mesh.enabled key still selects the mesh transport.
    assert T.transport_name(C.TpuConf(
        {C.MESH_ENABLED.key: True})) == "mesh"
    # Env is a whole-process default; explicit conf wins over it.
    monkeypatch.setenv("SRT_SHUFFLE_TRANSPORT", "hostfile")
    assert T.transport_name(C.TpuConf()) == "hostfile"
    assert T.transport_name(C.TpuConf(
        {C.SHUFFLE_TRANSPORT.key: "inprocess"})) == "inprocess"
    with pytest.raises(T.TransportError):
        T.transport_name(C.TpuConf({C.SHUFFLE_TRANSPORT.key: "ucx"}))


def test_register_third_party_transport():
    class Fake(T.ShuffleTransport):
        name = "fake"
    T.register_transport("fake", Fake)
    try:
        assert isinstance(T.get_transport("fake"), Fake)
        assert T.transport_name(C.TpuConf(
            {C.SHUFFLE_TRANSPORT.key: "fake"})) == "fake"
    finally:
        T._REGISTRY.pop("fake", None)
        T._INSTANCES.pop("fake", None)


# ---------------------------------------------------------------------------
# Shard wire format
# ---------------------------------------------------------------------------

def test_shard_blob_roundtrip_bit_exact():
    b = _batch([1, 2, 3, -7], [10, 20, 30, 40])
    out = shard_blob_to_batch(batch_to_shard_blob(b))
    assert _rows(out) == _rows(b)
    assert out.capacity == b.capacity


def test_shard_blob_detects_corruption():
    from spark_rapids_tpu.columnar.wire import WireCorruptionError
    blob = bytearray(batch_to_shard_blob(_batch([1], [2])))
    blob[len(blob) // 2] ^= 0xFF
    with pytest.raises(WireCorruptionError):
        shard_blob_to_batch(bytes(blob))


# ---------------------------------------------------------------------------
# Hostfile transport (single process)
# ---------------------------------------------------------------------------

def _hostfile_conf(tmp_path, **over):
    raw = {C.SHUFFLE_TRANSPORT_HOSTFILE_DIR.key: str(tmp_path)}
    raw.update({getattr(C, k).key: v for k, v in over.items()})
    return C.TpuConf(raw)


def test_hostfile_write_commit_fetch_roundtrip(tmp_path):
    conf = _hostfile_conf(tmp_path)
    w = HostFileTransport().open(conf, "xround", 2, owner=123)
    w.write_shard(0, _batch([1, 2], [3, 4]))
    w.write_shard(1, _batch([5], [6]))
    w.write_shard(0, _batch([7], [8]))
    w.commit()
    r = HostFileTransport().open(conf, "xround", 2)
    got0 = [row for h in r.fetch_shards(0) for row in _rows(h.get())]
    got1 = [row for h in r.fetch_shards(1) for row in _rows(h.get())]
    assert got0 == [(1, 3), (2, 4), (7, 8)]    # (worker, seq) order
    assert got1 == [(5, 6)]
    assert r.fetch_shards(1)[0].capacity >= 1  # manifest-known, no I/O
    r.close()
    w.close()
    assert not os.path.exists(w.root)          # last worker cleaned up


def test_hostfile_fetch_waits_for_commit(tmp_path):
    conf = _hostfile_conf(
        tmp_path, SHUFFLE_TRANSPORT_HOSTFILE_FETCH_TIMEOUT_MS=200)
    w = HostFileTransport().open(conf, "xuncommitted", 1, owner=9)
    w.write_shard(0, _batch([1], [2]))
    # No commit: the manifest is the publication barrier, so a fetch
    # sees NOTHING (not a torn shard set) and times out lost.
    r = HostFileTransport().open(conf, "xuncommitted", 1, owner=9)
    with pytest.raises(ShardLostError) as ei:
        r.fetch_shards(0)
    assert ei.value.fault_owner == 9
    w.invalidate()


def test_hostfile_lost_shard_raises_owner_tagged(tmp_path):
    conf = _hostfile_conf(tmp_path)
    w = HostFileTransport().open(conf, "xlost", 1, owner=42)
    w.write_shard(0, _batch([1], [2]))
    w.commit()
    # The shard vanishes at rest (a dead worker, a reaped spool).
    for root, _, files in os.walk(w.root):
        for f in files:
            if f.endswith(".shard"):
                os.remove(os.path.join(root, f))
    r = HostFileTransport().open(conf, "xlost", 1, owner=42)
    with pytest.raises(ShardLostError) as ei:
        r.fetch_shards(0)[0].get()
    assert ei.value.fault_owner == 42          # -> stage recompute
    w.invalidate()


def test_hostfile_corrupt_at_rest_refetches_once(tmp_path):
    T.reset_counters()
    conf = _hostfile_conf(tmp_path)
    w = HostFileTransport().open(conf, "xcorrupt", 1, owner=7)
    w.write_shard(0, _batch([1, 2, 3], [4, 5, 6]))
    w.commit()
    faults.configure("corrupt@transport:1", seed=3)
    try:
        r = HostFileTransport().open(conf, "xcorrupt", 1, owner=7)
        got = _rows(r.fetch_shards(0)[0].get())
        assert got == [(1, 4), (2, 5), (3, 6)]
        assert T.counters().get("remoteShardRefetches") == 1
    finally:
        faults.configure("")
        w.invalidate()


def test_valid_manifest_schema():
    from spark_rapids_tpu.parallel.transport.hostfile import \
        valid_manifest
    good = {"worker": "w0", "num_partitions": 2,
            "shards": {"0": [{"file": "w0/p00000-0000.shard",
                              "capacity": 4, "rows": 3}]}}
    assert valid_manifest(good)
    assert not valid_manifest(None)
    assert not valid_manifest([])
    assert not valid_manifest({})
    assert not valid_manifest({**good, "worker": 7})
    assert not valid_manifest({**good, "num_partitions": "2"})
    assert not valid_manifest({**good, "shards": "torn"})
    assert not valid_manifest({**good, "shards": {"0": "torn"}})
    assert not valid_manifest({**good, "shards": {"0": [{"file": 3}]}})
    assert not valid_manifest(
        {**good, "shards": {"0": [{"file": "x"}]}})   # no capacity


def test_hostfile_torn_manifest_reads_as_unpublished(tmp_path):
    """Regression (ISSUE 17): a manifest that lands WITHOUT the atomic
    rename — truncated JSON or a complete JSON document missing the
    commit() schema — must read as 'not yet published' (fetch keeps
    polling, then times out ShardLostError). It must never surface as a
    KeyError/TypeError deep inside fetch_shards."""
    import json
    conf = _hostfile_conf(
        tmp_path, SHUFFLE_TRANSPORT_HOSTFILE_FETCH_TIMEOUT_MS=250)
    w = HostFileTransport().open(conf, "xtorn", 1, owner=5)
    w.write_shard(0, _batch([1, 2], [3, 4]))
    w.commit()
    mpath = w._manifest_path()
    with open(mpath, encoding="utf-8") as f:
        full = f.read()
    # (a) truncated mid-document: unparseable JSON
    with open(mpath, "w", encoding="utf-8") as f:
        f.write(full[: len(full) // 2])
    r = HostFileTransport().open(conf, "xtorn", 1, owner=5)
    with pytest.raises(ShardLostError) as ei:
        r.fetch_shards(0)
    assert ei.value.fault_owner == 5
    # (b) parseable JSON but missing the commit() schema
    with open(mpath, "w", encoding="utf-8") as f:
        json.dump({"worker": "w0", "shards": "torn"}, f)
    r = HostFileTransport().open(conf, "xtorn", 1, owner=5)
    with pytest.raises(ShardLostError):
        r.fetch_shards(0)
    # (c) the complete manifest restored: published again, same data
    with open(mpath, "w", encoding="utf-8") as f:
        f.write(full)
    r = HostFileTransport().open(conf, "xtorn", 1, owner=5)
    assert _rows(r.fetch_shards(0)[0].get()) == [(1, 3), (2, 4)]
    w.invalidate()


def test_hostfile_invalidate_drops_spool(tmp_path):
    conf = _hostfile_conf(tmp_path)
    w = HostFileTransport().open(conf, "xinval", 1, owner=1)
    w.write_shard(0, _batch([1], [2]))
    w.commit()
    assert os.path.isdir(w.root)
    w.invalidate()
    assert not os.path.exists(w.root)
    # A recompute rewrites the same tag from scratch.
    w.write_shard(0, _batch([9], [10]))
    w.commit()
    r = HostFileTransport().open(conf, "xinval", 1)
    assert _rows(r.fetch_shards(0)[0].get()) == [(9, 10)]
    w.invalidate()


# ---------------------------------------------------------------------------
# Cross-process: 2 independent worker processes + socket rendezvous
# ---------------------------------------------------------------------------

def test_hostfile_cross_process_two_workers(tmp_path):
    """Two separate python processes map-write shards into the shared
    spool (announcing over the socket rendezvous); this process
    reduce-fetches their union through the same SPI — the multi-slice
    DCN stand-in with real process isolation."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "fixtures"))
    try:
        from hostfile_worker import worker_rows
    finally:
        sys.path.pop(0)
    script = os.path.join(os.path.dirname(__file__), "fixtures",
                          "hostfile_worker.py")
    n_parts = 3
    srv = RV.RendezvousServer()
    rv = f"{srv.addr[0]}:{srv.addr[1]}"
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)        # workers need no 8-device mesh
    try:
        procs = [subprocess.Popen(
            [sys.executable, script, str(tmp_path), "xproc", w,
             str(n_parts), rv],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for w in ("w0", "w1")]
        conf = _hostfile_conf(
            tmp_path,
            SHUFFLE_TRANSPORT_HOSTFILE_EXPECTED_WORKERS=2,
            SHUFFLE_TRANSPORT_HOSTFILE_RENDEZVOUS=rv,
            SHUFFLE_TRANSPORT_HOSTFILE_FETCH_TIMEOUT_MS=120000)
        r = HostFileTransport().open(conf, "xproc", n_parts)
        for p in range(n_parts):
            got = [row for h in r.fetch_shards(p)
                   for row in _rows(h.get())]
            want = []
            for w in ("w0", "w1"):     # manifest (worker) order
                keys, vals = worker_rows(w, p)
                want += list(zip(keys, vals))
            assert got == want, f"partition {p} diverged"
        for pr in procs:
            out, _ = pr.communicate(timeout=120)
            assert pr.returncode == 0, out.decode()
        r.close()
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# Query-level parity (integer agg => bit-identical across transports)
# ---------------------------------------------------------------------------

def _parity_query(session, data_dir):
    from spark_rapids_tpu.plan.logical import agg_sum, col
    a = session.read.parquet(os.path.join(data_dir, "t.parquet"))
    b = session.read.parquet(os.path.join(data_dir, "d.parquet"))
    j = a.join_on(b, ["k"], ["k2"], strategy="shuffle")
    return j.group_by("k").agg(
        agg_sum(col("v") + col("w")).alias("s")).order_by(col("k").asc())


@pytest.fixture(scope="module")
def parity_dir(tmp_path_factory):
    import pandas as pd
    d = tmp_path_factory.mktemp("transport_parity")
    rng = np.random.default_rng(11)
    pd.DataFrame({
        "k": rng.integers(0, 40, 4000),
        "v": rng.integers(0, 10**6, 4000),
    }).to_parquet(str(d / "t.parquet"))
    pd.DataFrame({
        "k2": np.arange(40),
        "w": rng.integers(0, 10**6, 40),
    }).to_parquet(str(d / "d.parquet"))
    return str(d)


@pytest.mark.parametrize(
    "transport", ["inprocess", "mesh", "hostfile", "objectstore"])
def test_join_agg_bit_identical_across_transports(transport, parity_dir,
                                                  tmp_path):
    from spark_rapids_tpu.api.dataframe import TpuSession

    def run(name):
        s = TpuSession()
        s.set("spark.rapids.sql.shuffle.transport", name)
        s.set(C.SHUFFLE_TRANSPORT_HOSTFILE_DIR.key, str(tmp_path))
        return _parity_query(s, parity_dir).collect()

    # Integer aggregation: no float-summation-order wiggle room — all
    # three transports must agree to the BIT.
    assert run(transport) == run("inprocess")
