"""Cost-based host/device placement (ISSUE 7 tentpole, plan/cost.py).

Small inputs cannot amortize the per-dispatch device sync floor, so the
planner places whole maximal subtrees on the host engine when the
footer-stats cost estimate says the host wins — and must leave the
legacy all-device plan untouched behind every gate (conf off, SRT_COST,
test mode, armed faults, non-inprocess transport, no file scan).
"""

import os
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq
import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.plan import cost as COST
from spark_rapids_tpu.plan.logical import agg_count, agg_sum, col


@pytest.fixture(scope="module")
def pq_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("cost_pq")
    rng = np.random.default_rng(11)
    n = 50_000
    papq.write_table(pa.table({
        "k": rng.integers(0, 64, n, dtype=np.int64),
        "v": rng.uniform(0, 1, n),
    }), os.path.join(d, "t.parquet"))
    return str(d)


def _scan_agg(session, pq_dir):
    return session.read.parquet(os.path.join(pq_dir, "t.parquet")) \
        .group_by("k").agg(agg_sum(col("v")).alias("s"))


def _session(**conf):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.cost.enabled", True)
    # The suite runs on a CPU-only backend, where the estimator zeroes
    # the sync floor (no tunnel). These scenarios exercise placement as
    # it behaves on real hardware, so opt into the tunnel constants.
    s.set("spark.rapids.sql.cost.assumeTunnel", True)
    for k, v in conf.items():
        s.set(k, v)
    return s


class TestCostEnabled:
    def test_conf_key_wins_over_env(self, monkeypatch):
        monkeypatch.setenv("SRT_COST", "0")
        conf = C.TpuConf({"spark.rapids.sql.cost.enabled": True})
        assert COST.cost_enabled(conf) is True

    def test_env_overrides_default(self, monkeypatch):
        monkeypatch.setenv("SRT_COST", "0")
        assert COST.cost_enabled(C.TpuConf()) is False
        monkeypatch.setenv("SRT_COST", "1")
        assert COST.cost_enabled(C.TpuConf()) is True

    def test_default_on(self, monkeypatch):
        monkeypatch.delenv("SRT_COST", raising=False)
        assert COST.cost_enabled(C.TpuConf()) is True


class TestStaticPlacement:
    def test_tiny_scan_plans_host(self, pq_dir):
        """A tiny parquet aggregate cannot amortize the sync floor: the
        whole subtree host-places and explain carries the estimate."""
        s = _session()
        phys = _scan_agg(s, pq_dir)._physical()
        assert phys.cost_report.placements == 1
        assert not phys.root_on_device
        assert "cost model: host placement" in phys.explain()

    def test_large_scan_stays_device(self, pq_dir):
        """The SF1-lineitem analog: input over the host-bytes ceiling
        never host-places, whatever the model says."""
        s = _session(**{"spark.rapids.sql.cost.maxHostBytes": 1024})
        phys = _scan_agg(s, pq_dir)._physical()
        assert phys.cost_report.placements == 0
        assert phys.root_on_device

    def test_device_wins_when_syncs_are_free(self, pq_dir):
        """Calibration constants drive the decision: with a zero sync
        floor and a fast device the model keeps the device plan."""
        s = _session(**{
            "spark.rapids.sql.cost.deviceSyncFloorMs": 0.0,
            "spark.rapids.sql.cost.deviceThroughputGBps": 10_000.0,
        })
        phys = _scan_agg(s, pq_dir)._physical()
        assert phys.cost_report.placements == 0
        assert phys.root_on_device

    def test_disabled_by_conf(self, pq_dir):
        s = _session(**{"spark.rapids.sql.cost.enabled": False})
        phys = _scan_agg(s, pq_dir)._physical()
        assert phys.cost_report.skipped == "disabled"
        assert phys.root_on_device

    def test_gated_in_test_mode(self, pq_dir):
        s = _session(**{
            "spark.rapids.sql.test.enabled": True,
            "spark.rapids.sql.test.allowedNonTpu": "",
        })
        phys = _scan_agg(s, pq_dir)._physical()   # must not raise
        assert phys.cost_report.skipped is not None
        assert phys.root_on_device

    def test_gated_under_armed_faults(self, pq_dir):
        s = _session(**{"spark.rapids.sql.test.faults": ""})
        phys = _scan_agg(s, pq_dir)._physical()
        assert "fault schedule" in phys.cost_report.skipped

    def test_gated_on_non_inprocess_transport(self, pq_dir):
        s = _session(**{"spark.rapids.sql.shuffle.transport": "hostfile"})
        phys = _scan_agg(s, pq_dir)._physical()
        assert "transport" in phys.cost_report.skipped

    def test_gated_without_file_scan(self):
        import spark_rapids_tpu as srt
        s = _session()
        df = s.create_dataframe({"k": [1, 2], "v": [1.0, 2.0]},
                                [("k", srt.INT64), ("v", srt.FLOAT64)])
        phys = df.group_by("k").agg(
            agg_sum(col("v")).alias("s"))._physical()
        assert "no footer-stats" in phys.cost_report.skipped
        assert phys.root_on_device

    def test_results_identical_on_vs_off(self, pq_dir):
        from spark_rapids_tpu.benchmarks.compare import compare_results
        on = _scan_agg(_session(), pq_dir).collect()
        off = _scan_agg(_session(**{
            "spark.rapids.sql.cost.enabled": False}), pq_dir).collect()
        assert compare_results(sorted(on), sorted(off), sort=True)

    def test_cost_metrics_surface(self, pq_dir):
        df = _scan_agg(_session(), pq_dir)
        df.collect()
        m = df.metrics()
        assert m["Cost@query"]["placements"] == 1
        assert m["Cost@query"]["estSyncs"] > 0

    def test_explain_mode_renders_estimates(self, pq_dir):
        s = _session(**{"spark.rapids.sql.cost.explain": True})
        report = _scan_agg(s, pq_dir)._physical().explain()
        assert "Cost model:" in report
        assert "syncs" in report


class TestRepartShortCircuit:
    """ISSUE 7 satellite: an exchange whose total input is below the
    cost threshold short-circuits to a host repartition — zero device
    round trips — and stays competitive with pandas."""

    N = 8

    def _repart(self, session, pq_dir):
        from spark_rapids_tpu.plan.logical import lit_col, murmur3_hash
        df = session.read.parquet(os.path.join(pq_dir, "t.parquet"))
        shuffled = df.repartition(self.N, col("k"))
        n = lit_col(self.N)
        bucket = ((murmur3_hash(col("k")) % n) + n) % n
        return shuffled.group_by(bucket.alias("bucket")) \
            .agg(agg_count().alias("n")).order_by("bucket")

    def test_tiny_repartition_places_host(self, pq_dir):
        phys = self._repart(_session(), pq_dir)._physical()
        assert phys.cost_report.placements == 1
        assert not phys.root_on_device
        # The repartition's exchange runs the host split path — no
        # ShuffleExchange materializes on the device engine.
        rows = phys.collect()
        ctx = phys.last_ctx
        assert not any(k.startswith("shuffle:") and k.endswith(":dev")
                       for k in ctx.cache)
        assert len(rows) <= self.N

    def test_repart_not_slower_than_pandas(self, pq_dir):
        """Regression pin for the r5 repart loss (0.24x vs pandas): the
        short-circuited host repartition must hold >= 0.8x a pandas
        implementation doing the same work (hash, materialize the N
        buckets, count each), plus a fixed allowance for the query
        machinery (admission, plan walk, the query's own second hash
        pass) that a 3-line numpy script does not pay and that is noise
        at bench scale. Medians over repeated warm runs keep CI stable;
        a regression to the per-partition device round-trip path is an
        order of magnitude, not a margin."""
        import pandas as pd
        from spark_rapids_tpu.exprs import hash as mh

        df = self._repart(_session(), pq_dir)

        def engine_once():
            t0 = time.perf_counter()
            df.collect()
            return time.perf_counter() - t0

        def pandas_once():
            t0 = time.perf_counter()
            tbl = papq.read_table(os.path.join(pq_dir, "t.parquet"),
                                  columns=["k"]).to_pandas()
            vals = tbl.k.to_numpy(np.int64)
            h = mh.hash_long(np, vals, np.uint32(mh.DEFAULT_SEED)) \
                .astype(np.int32)
            bucket = ((h.astype(np.int64) % self.N) + self.N) % self.N
            order = np.argsort(bucket, kind="stable")
            splits = np.cumsum(
                np.bincount(bucket, minlength=self.N))[:-1]
            parts = np.split(vals[order], splits)
            pd.Series({p: len(a) for p, a in enumerate(parts)}) \
                .sort_index()
            return time.perf_counter() - t0

        engine_once(), pandas_once()          # warm both paths
        eng = sorted(engine_once() for _ in range(5))[2]
        pdt = sorted(pandas_once() for _ in range(5))[2]
        assert eng <= pdt / 0.8 + 0.075, \
            f"host-short-circuited repart {eng:.4f}s vs pandas {pdt:.4f}s"


@pytest.mark.parametrize("qname", [
    "q1", "q6", "q22", "q11", "q14", "q19",
    # The join-heavy pair is the expensive half of the sweep: tier-1
    # keeps the scan/agg coverage fast, the CI replan matrix entry
    # (no slow filter) runs the full set.
    pytest.param("q3", marks=pytest.mark.slow),
    pytest.param("q5", marks=pytest.mark.slow)])
def test_tpch_parity_cost_on_vs_off(qname, tmp_path_factory):
    """Dual-engine parity across the suite: cost-model-on results match
    cost-model-off through the standard oracle comparator."""
    from spark_rapids_tpu.benchmarks import tpch
    d = getattr(test_tpch_parity_cost_on_vs_off, "_dir", None)
    if d is None:
        d = str(tmp_path_factory.mktemp("cost_tpch"))
        # Same scale/layout as tests/test_tpch.py: the cost-off runs
        # then reuse the device kernels that suite already compiled
        # (structural kernel-cache fingerprints) instead of adding a
        # whole second set of XLA executables to the process.
        tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
        test_tpch_parity_cost_on_vs_off._dir = d
    on = tpch.QUERIES[qname](_session(), d).collect()
    off = tpch.QUERIES[qname](_session(**{
        "spark.rapids.sql.cost.enabled": False}), d).collect()
    from spark_rapids_tpu.benchmarks.compare import compare_results
    assert compare_results(on, off, sort=True), qname


@pytest.mark.parametrize("qname", [
    "repart",
    # rollup+window q67 and the xbb pivot dominate the sweep's wall
    # clock; fast tier-1 keeps repart (the satellite's regression
    # shape), the CI replan matrix entry runs all three.
    pytest.param("q67", marks=pytest.mark.slow),
    pytest.param("xbb_q5", marks=pytest.mark.slow)])
def test_suites_parity_cost_on_vs_off(qname, tmp_path_factory):
    from spark_rapids_tpu.benchmarks import suites
    d = getattr(test_suites_parity_cost_on_vs_off, "_dir", None)
    if d is None:
        d = str(tmp_path_factory.mktemp("cost_suites"))
        # Mirrors tests/test_suites.py's datagen so the cost-off device
        # runs hit that suite's kernel-cache entries (see the TPC-H
        # parity note above).
        suites.generate(d, scale=0.01, files_per_table=2)
        test_suites_parity_cost_on_vs_off._dir = d
    on = suites.QUERIES[qname](_session(), d).collect()
    off = suites.QUERIES[qname](_session(**{
        "spark.rapids.sql.cost.enabled": False}), d).collect()
    from spark_rapids_tpu.benchmarks.compare import compare_results
    assert compare_results(on, off, sort=True), qname


class TestCalibration:
    """Cost-model self-calibration (ISSUE 11 satellite): observed sync
    floors / throughput EWMA into effective constants, clamped, with
    explicit conf keys always winning."""

    def setup_method(self):
        from spark_rapids_tpu.plan import cost
        cost.reset_calibration()

    def teardown_method(self):
        from spark_rapids_tpu.plan import cost
        cost.reset_calibration()

    def _conf(self, **raw):
        from spark_rapids_tpu.config import TpuConf
        # Calibration semantics are backend-independent; bypass the
        # CPU-only sync-floor zeroing so the constants stay observable.
        d = {"spark.rapids.sql.cost.assumeTunnel": True}
        d.update(raw)
        return TpuConf(d)

    def test_observation_moves_effective_values(self):
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.plan import cost
        conf = self._conf()
        base = float(C.COST_SYNC_FLOOR_MS.default)
        assert cost.effective_sync_floor_ms(conf) == base
        cost.observe(sync_floor_ms=base / 2, device_gbps=4.0)
        assert cost.effective_sync_floor_ms(conf) == base / 2
        assert cost.effective_device_gbps(conf) == 4.0
        # EWMA: a second observation blends, not replaces.
        cost.observe(sync_floor_ms=base, alpha=0.5)
        eff = cost.effective_sync_floor_ms(conf)
        assert base / 2 < eff < base

    def test_clamped_to_4x_band(self):
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.plan import cost
        conf = self._conf()
        base = float(C.COST_SYNC_FLOOR_MS.default)
        cost.observe(sync_floor_ms=base * 1000)
        assert cost.effective_sync_floor_ms(conf) == base * 4
        cost.reset_calibration()
        cost.observe(sync_floor_ms=base / 1000)
        assert cost.effective_sync_floor_ms(conf) == base / 4

    def test_explicit_conf_key_wins(self):
        from spark_rapids_tpu.plan import cost
        conf = self._conf(**{"spark.rapids.sql.cost.deviceSyncFloorMs":
                             33.0})
        cost.observe(sync_floor_ms=5.0)
        assert cost.effective_sync_floor_ms(conf) == 33.0

    def test_disabled_leaves_constants(self):
        from spark_rapids_tpu import config as C
        from spark_rapids_tpu.plan import cost
        conf = self._conf(**{"spark.rapids.sql.cost.calibration.enabled":
                             False})
        cost.observe(sync_floor_ms=1.0)
        assert cost.effective_sync_floor_ms(conf) == \
            float(C.COST_SYNC_FLOOR_MS.default)

    def test_error_pct_dampens_update(self):
        from spark_rapids_tpu.plan import cost
        cost.observe(sync_floor_ms=100.0)
        cost.observe(sync_floor_ms=10.0, error_pct=400.0, alpha=0.5)
        # weight = 0.5/(1+4) = 0.1 -> 0.9*100 + 0.1*10 = 91
        assert abs(cost.calibration_state()["sync_floor_ms"] - 91.0) < 1e-9

    def test_observe_query_reads_trace_spans(self, tmp_path):
        """A traced collect feeds real sync/upload spans into the
        calibration state."""
        from spark_rapids_tpu.plan import cost
        from spark_rapids_tpu.api.dataframe import TpuSession
        from spark_rapids_tpu.benchmarks import tpch
        d = str(tmp_path / "cal_tpch")
        tpch.generate(d, scale=0.003, files_per_table=1, seed=7)
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        s.set("spark.rapids.sql.trace.enabled", True)
        s.set("spark.rapids.sql.trace.level", "kernel")
        tpch.QUERIES["q6"](s, d).collect()
        state = cost.calibration_state()
        assert state["samples"] >= 1, state
        assert (state["sync_floor_ms"] or state["device_gbps"]), state
