"""Vectorized row materialization (ISSUE 4 satellite): the one-pass
``HostColumn.to_list`` / ``HostBatch.to_pylist`` must produce values
IDENTICAL (types included) to the reference per-row loop it replaced.
scripts/bench_rows.py measures the speedup; this file pins semantics.
"""

import math

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import (HostBatch, HostColumn,
                                            matrix_to_strings)


def _reference_to_list(col):
    """The pre-vectorization implementation, verbatim."""
    out = []
    for i in range(col.num_rows):
        if not col.validity[i]:
            out.append(None)
        elif col.dtype.is_string:
            out.append(bytes(col.data[i]).decode("utf-8", "replace"))
        elif col.dtype.is_boolean:
            out.append(bool(col.data[i]))
        elif col.dtype.is_floating:
            out.append(float(col.data[i]))
        else:
            out.append(int(col.data[i]))
    return out


def _check(col):
    got = col.to_list()
    want = _reference_to_list(col)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert type(g) is type(w), (g, w)
        if isinstance(w, float) and math.isnan(w):
            assert math.isnan(g)
        else:
            assert g == w, (g, w)
    return got


def test_ints_with_nulls():
    col = HostColumn.from_values(dt.INT64, [1, None, -5, 2 ** 40, None])
    assert _check(col) == [1, None, -5, 2 ** 40, None]


def test_int32_all_valid():
    col = HostColumn.from_values(dt.INT32, list(range(-3, 4)))
    _check(col)


def test_floats_including_nan_and_nulls():
    col = HostColumn.from_values(
        dt.FLOAT64, [1.5, None, float("nan"), -0.0, float("inf")])
    got = _check(col)
    assert got[3] == 0.0 and math.copysign(1.0, got[3]) == -1.0


def test_float32_widens_identically():
    col = HostColumn.from_values(dt.FLOAT32, [0.1, None, 3.25])
    _check(col)


def test_booleans():
    col = HostColumn.from_values(dt.BOOL, [True, None, False])
    assert _check(col) == [True, None, False]


def test_strings_object_array():
    col = HostColumn.from_values(dt.STRING, ["ab", None, "", "Ω≈ç"])
    assert _check(col) == ["ab", None, "", "Ω≈ç"]


def test_strings_matrix_layout():
    m = np.zeros((4, 3), np.uint8)
    m[0, :2] = list(b"hi")
    m[2, :3] = list(b"xyz")
    lens = np.array([2, 0, 3, 1], np.int32)
    val = np.array([True, False, True, True])
    col = matrix_to_strings(m, lens, val)
    assert col._data is None            # still lazy before to_list
    got = col.to_list()                 # must not materialize the object
    assert col._data is None            # array — it decodes the matrix
    assert got == ["hi", None, "xyz", "\x00"]
    assert _check(col) == got           # reference agrees (materializes)


def test_empty_column():
    col = HostColumn.from_values(dt.INT64, [])
    assert _check(col) == []


def test_batch_to_pylist_zip():
    hb = HostBatch.from_pydict(
        (("a", dt.INT64), ("s", dt.STRING)),
        {"a": [1, None, 3], "s": ["x", "y", None]})
    assert hb.to_pylist() == [(1, "x"), (None, "y"), (3, None)]
