"""Object-store shuffle transport (ISSUE 17): localhost stub, bounded
retry against 5xx bursts, shard loss at rest, injected fault kinds, the
manifest publication barrier, and the cluster chaos scenario — a
driver + 3 workers surviving shard loss and an availability burst
mid-query with at most one stage recompute and zero whole-query
retries.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import spark_rapids_tpu
from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import (HostBatch, HostColumn,
                                            device_to_host, host_to_device)
from spark_rapids_tpu.ops.base import ExecContext
from spark_rapids_tpu.parallel import broadcast_cache as BC
from spark_rapids_tpu.parallel import cluster as CL
from spark_rapids_tpu.parallel import transport as T
from spark_rapids_tpu.parallel.cluster.coordinator import ClusterExecInfo
from spark_rapids_tpu.parallel.transport.base import ShardLostError
from spark_rapids_tpu.parallel.transport.objectstore import (
    HttpObjectStoreBackend, ObjectMissingError, ObjectStoreStub,
    ObjectStoreTransport, ObjectStoreUnavailableError, make_backend)

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(spark_rapids_tpu.__file__)))


@pytest.fixture(autouse=True)
def _clean():
    faults.configure("")
    faults.reset_counters()
    T.reset_counters()
    yield
    CL.shutdown_coordinator()
    faults.configure("")
    faults.reset_counters()


@pytest.fixture()
def stub():
    s = ObjectStoreStub()
    yield s
    s.close()


def _batch(keys, vals):
    hb = HostBatch(
        ("k", "v"),
        [HostColumn(dt.INT64, np.asarray(keys, np.int64),
                    np.ones(len(keys), bool)),
         HostColumn(dt.INT64, np.asarray(vals, np.int64),
                    np.ones(len(vals), bool))])
    return host_to_device(hb)


def _rows(batch):
    return device_to_host(batch).to_pylist()


def _conf(stub, prefix="t", **over):
    raw = {C.SHUFFLE_TRANSPORT_OBJECTSTORE_ENDPOINT.key: stub.endpoint,
           C.SHUFFLE_TRANSPORT_OBJECTSTORE_PREFIX.key: prefix,
           C.SHUFFLE_TRANSPORT_OBJECTSTORE_BACKOFF_MS.key: 5}
    raw.update({getattr(C, k).key: v for k, v in over.items()})
    return C.TpuConf(raw)


# ---------------------------------------------------------------------------
# Backend + stub
# ---------------------------------------------------------------------------

def test_stub_backend_put_get_list_delete(stub):
    b = make_backend(stub.endpoint, timeout_s=2.0)
    assert isinstance(b, HttpObjectStoreBackend)
    b.put("a/x", b"one")
    b.put("a/y", b"two")
    b.put("b/z", b"three")
    assert b.get("a/y") == b"two"
    assert b.list_keys("a/") == ["a/x", "a/y"]
    b.delete("a/x")
    assert b.list_keys("a/") == ["a/y"]
    with pytest.raises(ObjectMissingError):
        b.get("a/x")


def test_stub_5xx_surfaces_typed_unavailable(stub):
    b = make_backend(stub.endpoint, timeout_s=2.0)
    b.put("k", b"v")
    stub.fail_next(1)
    with pytest.raises(ObjectStoreUnavailableError):
        b.get("k")
    assert b.get("k") == b"v"      # burst over: healthy again


def test_stub_http_admin_surface_steers_chaos(stub):
    """The same chaos the in-process setters drive must be reachable
    over HTTP — that is what out-of-process CI workers use."""
    b = make_backend(stub.endpoint, timeout_s=2.0)
    b.put("c/s1", b"x")
    b.put("c/s2", b"y")

    def admin(path):
        req = urllib.request.Request(f"{stub.endpoint}{path}",
                                     method="POST")
        with urllib.request.urlopen(req, timeout=2.0) as r:
            return r.read()

    dropped = json.loads(admin("/admin/drop?prefix=c/s1"))
    assert dropped == ["c/s1"]
    admin("/admin/fail?n=1&code=503")
    with pytest.raises(ObjectStoreUnavailableError):
        b.get("c/s2")
    stats = json.loads(urllib.request.urlopen(
        f"{stub.endpoint}/admin/stats", timeout=2.0).read())
    assert stats["failed"] >= 1


# ---------------------------------------------------------------------------
# Session SPI: roundtrip, publication barrier, retry, loss
# ---------------------------------------------------------------------------

def test_objectstore_write_commit_fetch_roundtrip(stub):
    conf = _conf(stub)
    w = ObjectStoreTransport().open(conf, "xround", 2, owner=123)
    w.write_shard(0, _batch([1, 2], [3, 4]))
    w.write_shard(1, _batch([5], [6]))
    w.write_shard(0, _batch([7], [8]))
    w.commit()
    r = ObjectStoreTransport().open(conf, "xround", 2)
    got0 = [row for h in r.fetch_shards(0) for row in _rows(h.get())]
    got1 = [row for h in r.fetch_shards(1) for row in _rows(h.get())]
    assert got0 == [(1, 3), (2, 4), (7, 8)]    # (worker, seq) order
    assert got1 == [(5, 6)]
    assert r.fetch_shards(1)[0].capacity >= 1  # manifest-known, no I/O
    r.close()
    w.close()
    assert stub.keys("t/xround") == []         # last owner cleaned up


def test_objectstore_fetch_waits_for_manifest(stub):
    conf = _conf(
        stub, SHUFFLE_TRANSPORT_OBJECTSTORE_FETCH_TIMEOUT_MS=200)
    w = ObjectStoreTransport().open(conf, "xbarrier", 1, owner=9)
    w.write_shard(0, _batch([1], [2]))
    # No commit: shard objects are durable but INVISIBLE — the manifest
    # PUT is the publication barrier.
    r = ObjectStoreTransport().open(conf, "xbarrier", 1, owner=9)
    with pytest.raises(ShardLostError) as ei:
        r.fetch_shards(0)
    assert ei.value.fault_owner == 9
    w.invalidate()


def test_objectstore_torn_manifest_reads_as_unpublished(stub):
    """Same regression contract as the hostfile spool: a torn or
    schema-incomplete manifest object is 'not yet published', never a
    crash inside fetch_shards."""
    conf = _conf(
        stub, SHUFFLE_TRANSPORT_OBJECTSTORE_FETCH_TIMEOUT_MS=200)
    b = make_backend(stub.endpoint, timeout_s=2.0)
    w = ObjectStoreTransport().open(conf, "xtorn", 1, owner=4)
    w.write_shard(0, _batch([1], [2]))
    w.commit()
    mkey = w._manifest_key()
    full = b.get(mkey)
    for torn in (full[: len(full) // 2],
                 json.dumps({"worker": "w", "shards": "torn"}).encode()):
        b.put(mkey, torn)
        r = ObjectStoreTransport().open(conf, "xtorn", 1, owner=4)
        with pytest.raises(ShardLostError) as ei:
            r.fetch_shards(0)
        assert ei.value.fault_owner == 4
    b.put(mkey, full)                          # restored: published
    r = ObjectStoreTransport().open(conf, "xtorn", 1, owner=4)
    assert _rows(r.fetch_shards(0)[0].get()) == [(1, 2)]
    w.invalidate()


def test_5xx_burst_absorbed_by_bounded_retry(stub):
    conf = _conf(stub, SHUFFLE_TRANSPORT_OBJECTSTORE_RETRIES=4)
    w = ObjectStoreTransport().open(conf, "xburst", 1, owner=1)
    w.write_shard(0, _batch([1], [2]))
    w.commit()
    stub.fail_next(3)                          # every op retries past it
    r = ObjectStoreTransport().open(conf, "xburst", 1, owner=1)
    assert _rows(r.fetch_shards(0)[0].get()) == [(1, 2)]
    assert T.counters().get("objectstoreRetries", 0) >= 1
    w.invalidate()


def test_retry_exhaustion_surfaces_typed_unavailable(stub):
    conf = _conf(stub, SHUFFLE_TRANSPORT_OBJECTSTORE_RETRIES=1)
    w = ObjectStoreTransport().open(conf, "xdown", 1, owner=1)
    stub.fail_next(10)
    with pytest.raises(ObjectStoreUnavailableError):
        w.write_shard(0, _batch([1], [2]))


def test_shard_loss_at_rest_raises_owner_tagged(stub):
    conf = _conf(stub)
    w = ObjectStoreTransport().open(conf, "xloss", 1, owner=42)
    w.write_shard(0, _batch([1], [2]))
    w.commit()
    r = ObjectStoreTransport().open(conf, "xloss", 1, owner=42)
    handles = r.fetch_shards(0)
    stub.drop("t/xloss/")                      # the chaos matrix verb
    # the manifest is gone too, but the handle already points at its key
    with pytest.raises(ShardLostError) as ei:
        handles[0].get()
    assert ei.value.fault_owner == 42          # lineage recompute target
    assert T.counters().get("remoteShardsLost", 0) == 1


def test_corrupt_at_rest_refetches_once(stub):
    conf = _conf(stub)
    w = ObjectStoreTransport().open(conf, "xcorrupt", 1, owner=7)
    w.write_shard(0, _batch([1, 2, 3], [4, 5, 6]))
    w.commit()
    faults.configure("corrupt@transport:1", seed=3)
    try:
        r = ObjectStoreTransport().open(conf, "xcorrupt", 1, owner=7)
        got = _rows(r.fetch_shards(0)[0].get())
        assert got == [(1, 4), (2, 5), (3, 6)]
        assert T.counters().get("remoteShardRefetches") == 1
    finally:
        faults.configure("")
        w.invalidate()


# ---------------------------------------------------------------------------
# Injected fault kinds (chaos matrix verbs)
# ---------------------------------------------------------------------------

def test_fault_unavailable_objectstore_absorbed_by_retry(stub):
    conf = _conf(stub, SHUFFLE_TRANSPORT_OBJECTSTORE_RETRIES=3)
    faults.configure("unavailable@objectstore:1", seed=5)
    try:
        w = ObjectStoreTransport().open(conf, "xfault", 1, owner=1)
        w.write_shard(0, _batch([1], [2]))
        w.commit()
        r = ObjectStoreTransport().open(conf, "xfault", 1, owner=1)
        assert _rows(r.fetch_shards(0)[0].get()) == [(1, 2)]
        assert T.counters().get("objectstoreRetries", 0) >= 1
    finally:
        faults.configure("")
        w.invalidate()


def test_fault_slowput_transport_is_latency_not_error(stub):
    conf = _conf(stub)
    faults.configure("slowput@transport:1", seed=5)
    try:
        w = ObjectStoreTransport().open(conf, "xslow", 1, owner=1)
        t0 = time.monotonic()
        w.write_shard(0, _batch([1], [2]))
        assert time.monotonic() - t0 >= 0.2    # injected latency
        w.commit()
        r = ObjectStoreTransport().open(conf, "xslow", 1, owner=1)
        assert _rows(r.fetch_shards(0)[0].get()) == [(1, 2)]
        assert T.counters().get("slowPuts", 0) == 1
    finally:
        faults.configure("")
        w.invalidate()


def test_injected_lostshard_deletes_at_rest_first(stub):
    conf = _conf(stub)
    w = ObjectStoreTransport().open(conf, "xdel", 1, owner=3)
    w.write_shard(0, _batch([1], [2]))
    w.commit()
    faults.configure("lostshard@transport:1", seed=2)
    try:
        r = ObjectStoreTransport().open(conf, "xdel", 1, owner=3)
        with pytest.raises(ShardLostError):
            r.fetch_shards(0)[0].get()
        # recovery must REWRITE, not re-read a survivor
        assert not any(k.endswith(".shard") for k in stub.keys("t/xdel"))
    finally:
        faults.configure("")
        w.invalidate()


# ---------------------------------------------------------------------------
# Broadcast artifact cache (tentpole leg c) through the objectstore
# ---------------------------------------------------------------------------

def _bcast_ctx(stub, wid, exchange, gens=None, **over):
    """One simulated cluster process: an ExecContext whose installed
    ClusterExecInfo tags ``exchange`` as broadcast stage 4 of a query
    with plan fingerprint ``feedface`` on the objectstore store."""
    ctx = ExecContext(conf=_conf(stub, prefix="bc", **over))
    ctx.cache["cluster"] = ClusterExecInfo(
        "", wid, {}, store_kind="objectstore",
        store_endpoint=stub.endpoint, store_prefix="bc",
        bcast_tags={id(exchange): 4}, bcast_deps={4: [1, 2]},
        plan_fp="feedface",
        gen_source=(lambda: gens) if gens is not None else None)
    return ctx


def test_broadcast_cache_publish_then_adopted_by_peer(stub):
    """The first process to build a broadcast single publishes it; a
    peer process of the same query adopts the committed blob instead of
    re-collecting — and the counters bench.py records prove it."""
    ex = object()
    single = _batch([1, 2, 3], [10, 20, 30])
    BC.maybe_publish(_bcast_ctx(stub, "w0", ex), ex, single)
    assert T.counters().get("broadcastCachePublishes") == 1
    assert stub.keys("bc/bc-feedface-s4-g0/")      # content-addressed key
    hit = BC.maybe_fetch(_bcast_ctx(stub, "w1", ex), ex)
    assert hit is not None
    _handle, got = hit
    assert _rows(got) == _rows(single)
    assert T.counters().get("broadcastCacheHits") == 1


def test_broadcast_cache_unpublished_and_loss_degrade_to_miss(stub):
    """Not-yet-published and lost-at-rest both mean: build locally.
    Never an error, never a recompute."""
    ex = object()
    dst = _bcast_ctx(stub, "w1", ex)
    assert BC.maybe_fetch(dst, ex) is None          # nobody published yet
    BC.maybe_publish(_bcast_ctx(stub, "w0", ex), ex, _batch([1], [2]))
    stub.drop("bc/")                 # blobs AND manifest lost at rest
    assert BC.maybe_fetch(dst, ex) is None          # loss = miss
    assert T.counters().get("broadcastCacheMisses") >= 2
    assert faults.counters().get("stageRecomputes", 0) == 0


def test_broadcast_cache_generation_bump_invalidates(stub):
    """A recomputed upstream stage bumps its generation, which changes
    the cache tag — a cached build of pre-recompute inputs is simply
    never found."""
    ex = object()
    BC.maybe_publish(_bcast_ctx(stub, "w0", ex, gens={1: 0, 2: 0}),
                     ex, _batch([7], [8]))
    assert BC.maybe_fetch(
        _bcast_ctx(stub, "w1", ex, gens={1: 0, 2: 0}), ex) is not None
    assert BC.maybe_fetch(
        _bcast_ctx(stub, "w2", ex, gens={1: 1, 2: 0}), ex) is None


def test_broadcast_cache_disabled_is_inert(stub):
    ex = object()
    ctx = _bcast_ctx(stub, "w0", ex, BROADCAST_CACHE_ENABLED=False)
    BC.maybe_publish(ctx, ex, _batch([1], [2]))
    assert stub.keys("bc/") == []
    assert BC.maybe_fetch(ctx, ex) is None
    assert T.counters().get("broadcastCachePublishes", 0) == 0


# ---------------------------------------------------------------------------
# Cluster chaos (acceptance scenario 2): shard loss + 5xx burst
# ---------------------------------------------------------------------------

def _spawn_worker(addr, wid, extra_env=None):
    env = dict(os.environ)
    env.pop("SRT_FAULTS", None)
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(
        [sys.executable, "-m",
         "spark_rapids_tpu.parallel.cluster.worker",
         "--coordinator", addr, "--worker-id", wid],
        env=env, cwd=REPO_ROOT)


def _stop(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=20)
        except Exception:
            p.kill()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_objstore"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


@pytest.mark.slow      # CI runs this via the objectstore-loss entry
def test_cluster_survives_shard_loss_and_5xx_burst(data_dir, stub):
    """Driver + 3 workers on the objectstore transport. Mid-query chaos:
    one worker loses a fetched dep shard at rest (lostshard fires inside
    its transport fetch) while the store serves a 5xx burst. The query
    must finish bit-identical with EXACTLY one stage recompute and zero
    whole-query retries — loss is repaired by lineage, bursts by the
    bounded retry loop, never by rerunning the query."""
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    want = tpch.QUERIES["q3"](s, data_dir).collect()

    sc = TpuSession()
    sc.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    sc.set("spark.rapids.sql.cluster.enabled", True)
    sc.set("spark.rapids.sql.shuffle.transport", "objectstore")
    sc.set(C.SHUFFLE_TRANSPORT_OBJECTSTORE_ENDPOINT.key, stub.endpoint)
    sc.set("spark.rapids.sql.cluster.minWorkers", 3)
    co = CL.get_coordinator(sc.conf)
    addr = f"{co.addr[0]}:{co.addr[1]}"
    procs = [
        _spawn_worker(addr, "w0",
                      extra_env={"SRT_FAULTS": "lostshard@transport:1"}),
        _spawn_worker(addr, "w1"),
        _spawn_worker(addr, "w2"),
    ]
    stub.fail_next(5)                          # availability burst
    try:
        c0 = dict(faults.counters())
        got = tpch.QUERIES["q3"](sc, data_dir).collect()
        c1 = faults.counters()
        delta = lambda k: c1.get(k, 0) - c0.get(k, 0)
        assert got == want                       # bit-identical
        assert delta("stageRecomputes") <= 1     # at most ONE per loss
        assert delta("retriesAttempted") == 0    # never a dead query
    finally:
        _stop(procs)
