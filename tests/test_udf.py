"""UDF tier (VERDICT r3 item 6; ref udf-compiler/Instruction.scala +
CatalystExpressionBuilder.scala for compilation,
GpuArrowEvalPythonExec.scala:494 for the python fallback): AST
compilation of the restricted subset, the host-roundtrip fallback with
explain visibility, and fuzzed equivalence of compiled UDFs against
direct python application."""

import math
import random

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.plan.logical import col
from spark_rapids_tpu.udf import UdfCompileError, compile_udf, udf


@pytest.fixture
def session():
    return TpuSession()


@pytest.fixture
def df(session):
    return session.create_dataframe(
        {"x": [1.0, 2.5, -3.0, 4.0, None],
         "y": [10.0, 0.5, 2.0, -1.0, 3.0],
         "s": ["Ab", "cD", None, "ef", "GH"]},
        [("x", srt.FLOAT64), ("y", srt.FLOAT64), ("s", srt.STRING)],
        num_partitions=2)


class TestCompile:
    def test_lambda_arithmetic_compiles(self):
        f = udf(lambda a, b: a * 2.0 + b - 1.5)
        assert f.compiled

    def test_def_with_conditional_compiles(self):
        @udf
        def clamp(a, lo, hi):
            return lo if a < lo else (hi if a > hi else a)
        assert clamp.compiled

    def test_builtins_compile(self):
        assert udf(lambda a, b: min(abs(a), max(b, 1.0))).compiled
        assert udf(lambda s: len(s)).compiled
        assert udf(lambda s: s.upper()).compiled

    def test_loop_does_not_compile(self):
        @udf
        def total(a):
            out = 0
            for i in range(3):
                out += a
            return out
        assert not total.compiled
        assert "single return" in total.compile_error

    def test_captured_literal_inlines(self, session):
        k = 7.0
        f = udf(lambda a: a + k)
        assert f.compiled
        df = session.create_dataframe(
            {"a": [1.0, 2.0]}, [("a", srt.FLOAT64)])
        assert df.select(f(col("a")).alias("z")).collect() == \
            [(8.0,), (9.0,)]

    def test_nonliteral_capture_does_not_compile(self):
        table = {1: 2}
        f = udf(lambda a: table)
        assert not f.compiled
        assert "captured variable" in f.compile_error

    def test_unknown_call_does_not_compile(self):
        f = udf(lambda a: math.erf(a))
        assert not f.compiled


class TestExecution:
    def test_compiled_udf_runs_on_device(self, df):
        f = udf(lambda a, b: a * 2.0 + b)
        q = df.select("x", f(col("x"), col("y")).alias("z"))
        dev = q.collect()
        host = q.collect_host()
        assert dev == host
        for x, y, z in [(r[0], None, r[1]) for r in dev if r[0] is None]:
            assert z is None
        report = q.explain()
        assert "pyudf" not in report     # native expressions, no fallback

    def test_fallback_udf_matches_python(self, df):
        f = udf(lambda a: math.erf(a) if a is not None else None,
                return_type="double")
        assert not f.compiled
        q = df.select("x", f(col("x")).alias("z"))
        dev = dict(q.collect())
        host = dict(q.collect_host())
        assert dev == host
        for x, z in dev.items():
            if x is not None:
                assert z == pytest.approx(math.erf(x))

    def test_fallback_reason_in_explain(self, df):
        f = udf(lambda a: math.erf(a) if a is not None else None,
                return_type="double")
        report = df.select(f(col("x")).alias("z")).explain()
        assert "could not be compiled" in report

    def test_fallback_after_filter(self, df):
        """Selection vectors reach the host roundtrip correctly."""
        f = udf(lambda a: math.floor(a * 10.0) if a is not None else None,
                return_type="double")
        q = df.filter(col("y") > 0).select("x", f(col("x")).alias("z"))
        assert sorted(q.collect(), key=repr) == \
            sorted(q.collect_host(), key=repr)

    def test_string_udf(self, df):
        f = udf(lambda s: s.upper())
        s2 = TpuSession()
        s2.set("spark.rapids.sql.incompatibleOps.enabled", True)
        df2 = s2.create_dataframe(
            {"s": ["Ab", "cD", None]}, [("s", srt.STRING)])
        q = df2.select(f(col("s")).alias("u"))
        assert q.collect() == q.collect_host() == [("AB",), ("CD",),
                                                   (None,)]


class TestFuzzedEquivalence:
    """Random expressions from the compilable grammar: compiled-UDF
    results must equal direct python application (the udf-compiler test
    ideology — OpcodeSuite's equivalence checks)."""

    def _gen_expr(self, rng, depth=0):
        leaves = ["a", "b", "1.5", "2.0", "0.25"]
        if depth > 2 or rng.random() < 0.3:
            return rng.choice(leaves)
        kind = rng.choice(["bin", "call", "cond"])
        if kind == "bin":
            op = rng.choice(["+", "-", "*"])
            return (f"({self._gen_expr(rng, depth + 1)} {op} "
                    f"{self._gen_expr(rng, depth + 1)})")
        if kind == "call":
            fn = rng.choice(["abs", "min", "max"])
            if fn == "abs":
                return f"abs({self._gen_expr(rng, depth + 1)})"
            return (f"{fn}({self._gen_expr(rng, depth + 1)}, "
                    f"{self._gen_expr(rng, depth + 1)})")
        return (f"({self._gen_expr(rng, depth + 1)} if "
                f"{self._gen_expr(rng, depth + 1)} > 0.0 else "
                f"{self._gen_expr(rng, depth + 1)})")

    @pytest.mark.parametrize("seed", range(8))
    def test_fuzzed(self, session, seed, tmp_path):
        rng = random.Random(seed)
        src = f"lambda a, b: {self._gen_expr(rng)}"
        # The compiler reads real source; give the lambda a file.
        mod = tmp_path / f"udf_fuzz_{seed}.py"
        mod.write_text(f"f = {src}\n")
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            f"udf_fuzz_{seed}", mod)
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        f = m.f
        cf = udf(f)
        assert cf.compiled, src
        xs = [rng.uniform(-5, 5) for _ in range(40)]
        ys = [rng.uniform(-5, 5) for _ in range(40)]
        df = session.create_dataframe(
            {"a": xs, "b": ys},
            [("a", srt.FLOAT64), ("b", srt.FLOAT64)], num_partitions=2)
        got = [r[0] for r in
               df.select(cf(col("a"), col("b")).alias("z")).collect()]
        want = [f(x, y) for x, y in zip(xs, ys)]
        assert got == pytest.approx(want), src
