"""Physical operator tests: device engine vs host oracle
(SparkQueryCompareTestSuite analog at operator level)."""

import math

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu import exprs as E
from spark_rapids_tpu.exprs.base import BoundReference as Ref, lit
from spark_rapids_tpu import ops
from spark_rapids_tpu.ops import (
    AggSpec, Average, Count, CountStar, ExecContext, FilterExec, First,
    GlobalLimitExec, HashAggregateExec, InMemorySourceExec, Last,
    LocalLimitExec, Max, Min, ProjectExec, RangeExec, SortExec, SortOrder,
    Sum, UnionExec)

from harness import assert_rows_equal


def source(schema, data, num_partitions=1, batches_per_partition=1):
    """Build an InMemorySourceExec, optionally splitting rows."""
    hb = HostBatch.from_pydict(schema, data)
    rows = hb.to_pylist()
    names = tuple(n for n, _ in schema)
    parts = []
    per = max(1, -(-len(rows) // num_partitions))
    chunks = [rows[i:i + per] for i in range(0, len(rows), per)] or [[]]
    while len(chunks) < num_partitions:
        chunks.append([])
    for chunk in chunks[:num_partitions]:
        bper = max(1, -(-len(chunk) // batches_per_partition))
        bs = []
        for j in range(0, max(len(chunk), 1), bper):
            sub = chunk[j:j + bper]
            cols = {n: [r[ci] for r in sub] for ci, n in enumerate(names)}
            bs.append(HostBatch.from_pydict(schema, cols))
        parts.append(bs)
    return InMemorySourceExec(tuple(schema), parts)


def compare_engines(plan, expected=None, approx_float=False,
                    sort_result=False):
    dev = plan.collect(device=True)
    host = plan.collect(device=False)
    expected = list(expected) if expected is not None else None
    if sort_result:
        keyf = lambda r: tuple((v is None, str(v)) for v in r)
        dev = sorted(dev, key=keyf)
        host = sorted(host, key=keyf)
        if expected is not None:
            expected = sorted(expected, key=keyf)
    assert_rows_equal(dev, host, approx_float, "device vs host engine")
    if expected is not None:
        assert_rows_equal(dev, expected, approx_float, "device vs oracle")
    return dev


SCHEMA = [("k", dt.STRING), ("v", dt.INT32), ("x", dt.FLOAT64)]
DATA = {
    "k": ["a", "b", "a", None, "b", "a", "c", None],
    "v": [1, 2, 3, 4, None, 6, 7, 8],
    "x": [1.0, 2.5, float("nan"), 4.0, 5.0, None, 7.5, 8.0],
}


class TestBasicOps:
    def test_project(self):
        plan = ProjectExec(source(SCHEMA, DATA),
                           [("v2", E.Multiply(Ref(1, dt.INT32), lit(2))),
                            ("up", E.Upper(Ref(0, dt.STRING)))])
        compare_engines(plan,
                        [(2, "A"), (4, "B"), (6, "A"), (8, None), (None, "B"),
                         (12, "A"), (14, "C"), (16, None)])

    def test_filter(self):
        plan = FilterExec(source(SCHEMA, DATA),
                          E.GreaterThan(Ref(1, dt.INT32), lit(3)))
        compare_engines(plan, [(None, 4, 4.0), ("a", 6, None),
                               ("c", 7, 7.5), (None, 8, 8.0)])

    def test_filter_multibatch(self):
        plan = FilterExec(source(SCHEMA, DATA, batches_per_partition=3),
                          E.IsNotNull(Ref(0, dt.STRING)))
        dev = compare_engines(plan)
        assert len(dev) == 6

    def test_union(self):
        s1 = source(SCHEMA, DATA)
        s2 = source(SCHEMA, DATA)
        plan = UnionExec(s1, s2)
        dev = compare_engines(plan)
        assert len(dev) == 16

    def test_limits(self):
        plan = LocalLimitExec(source(SCHEMA, DATA, batches_per_partition=4),
                              3)
        dev = compare_engines(plan)
        assert len(dev) == 3
        plan = GlobalLimitExec(source(SCHEMA, DATA), 5)
        assert len(compare_engines(plan)) == 5

    def test_range(self):
        plan = RangeExec(0, 100, 7, num_partitions=3, batch_rows=8)
        dev = compare_engines(plan)
        assert [r[0] for r in dev] == list(range(0, 100, 7))

    def test_range_negative_step(self):
        plan = RangeExec(10, -10, -3, num_partitions=2, batch_rows=4)
        dev = compare_engines(plan)
        assert [r[0] for r in dev] == list(range(10, -10, -3))


class TestSort:
    def test_sort_int_asc_desc(self):
        plan = SortExec(source(SCHEMA, DATA, batches_per_partition=2),
                        [SortOrder(Ref(1, dt.INT32))])
        dev = compare_engines(plan)
        assert [r[1] for r in dev] == [None, 1, 2, 3, 4, 6, 7, 8]
        plan = SortExec(source(SCHEMA, DATA),
                        [SortOrder(Ref(1, dt.INT32), ascending=False,
                                   nulls_first=False)])
        dev = compare_engines(plan)
        assert [r[1] for r in dev] == [8, 7, 6, 4, 3, 2, 1, None]

    def test_sort_string_then_int(self):
        plan = SortExec(source(SCHEMA, DATA),
                        [SortOrder(Ref(0, dt.STRING)),
                         SortOrder(Ref(1, dt.INT32), ascending=False,
                                   nulls_first=False)])
        dev = compare_engines(plan)
        assert [(r[0], r[1]) for r in dev] == [
            (None, 8), (None, 4), ("a", 6), ("a", 3), ("a", 1),
            ("b", 2), ("b", None), ("c", 7)]

    def test_sort_float_nan_greatest(self):
        plan = SortExec(source(SCHEMA, DATA),
                        [SortOrder(Ref(2, dt.FLOAT64), nulls_first=False)])
        dev = compare_engines(plan)
        xs = [r[2] for r in dev]
        assert xs[:5] == [1.0, 2.5, 4.0, 5.0, 7.5]
        assert xs[5] == 8.0
        assert math.isnan(xs[6]) and xs[7] is None

    def test_host_sort_negative_nan_greatest(self):
        # Sign-bit NaN must sort greatest on the host oracle too, matching
        # the device kernel's nan_word handling (Java Double.compare).
        import struct as _struct
        from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
        from spark_rapids_tpu.ops.sort import sort_host_batch
        neg_nan = _struct.unpack("<d", _struct.pack("<Q",
                                                    0xFFF8000000000000))[0]
        vals = np.array([neg_nan, 1.0, -2.0, float("inf")], np.float64)
        hb = HostBatch(("x",), [HostColumn(dt.FLOAT64, vals,
                                           np.ones(4, np.bool_))])
        out = sort_host_batch(hb, [SortOrder(Ref(0, dt.FLOAT64))])
        xs = out.columns[0].data
        assert list(xs[:3]) == [-2.0, 1.0, float("inf")]
        assert math.isnan(xs[3])

    def test_sort_stable_ties(self):
        schema = [("a", dt.INT32), ("b", dt.INT32)]
        data = {"a": [1, 1, 1, 0, 0], "b": [10, 20, 30, 40, 50]}
        plan = SortExec(source(schema, data),
                        [SortOrder(Ref(0, dt.INT32))])
        dev = compare_engines(plan)
        assert [r[1] for r in dev] == [40, 50, 10, 20, 30]


class TestAggregate:
    def test_global_agg(self):
        plan = HashAggregateExec(
            source(SCHEMA, DATA, batches_per_partition=3), [],
            [AggSpec("cnt", CountStar(None)),
             AggSpec("cv", Count(Ref(1, dt.INT32))),
             AggSpec("sv", Sum(Ref(1, dt.INT32))),
             AggSpec("mn", Min(Ref(1, dt.INT32))),
             AggSpec("mx", Max(Ref(1, dt.INT32))),
             AggSpec("av", Average(Ref(1, dt.INT32)))])
        compare_engines(plan, [(8, 7, 31, 1, 8, 31 / 7)],
                        approx_float=True)

    def test_group_by_string_key(self):
        plan = HashAggregateExec(
            source(SCHEMA, DATA, batches_per_partition=2),
            [("k", Ref(0, dt.STRING))],
            [AggSpec("cnt", CountStar(None)),
             AggSpec("s", Sum(Ref(1, dt.INT32)))])
        compare_engines(plan,
                        [("a", 3, 10), ("b", 2, 2), (None, 2, 12),
                         ("c", 1, 7)], sort_result=True)

    def test_group_by_min_max_float_nan(self):
        plan = HashAggregateExec(
            source(SCHEMA, DATA), [("k", Ref(0, dt.STRING))],
            [AggSpec("mn", Min(Ref(2, dt.FLOAT64))),
             AggSpec("mx", Max(Ref(2, dt.FLOAT64)))])
        dev = compare_engines(plan, sort_result=True)
        bykey = {r[0]: r[1:] for r in dev}
        # group a: [1.0, nan, null] -> min 1.0, max NaN (NaN greatest)
        assert bykey["a"][0] == 1.0 and math.isnan(bykey["a"][1])
        assert bykey["b"] == (2.5, 5.0)

    def test_first_last(self):
        plan = HashAggregateExec(
            source(SCHEMA, DATA, batches_per_partition=2),
            [("k", Ref(0, dt.STRING))],
            [AggSpec("f", First(Ref(1, dt.INT32))),
             AggSpec("l", Last(Ref(1, dt.INT32)))])
        compare_engines(plan,
                        [("a", 1, 6), ("b", 2, 2), (None, 4, 8),
                         ("c", 7, 7)], sort_result=True)

    def test_avg_all_null_group(self):
        schema = [("k", dt.INT32), ("v", dt.INT32)]
        data = {"k": [1, 1, 2], "v": [None, None, 5]}
        plan = HashAggregateExec(
            source(schema, data), [("k", Ref(0, dt.INT32))],
            [AggSpec("s", Sum(Ref(1, dt.INT32))),
             AggSpec("a", Average(Ref(1, dt.INT32)))])
        compare_engines(plan, [(1, None, None), (2, 5, 5.0)],
                        sort_result=True)

    def test_partial_final_roundtrip(self):
        # Two-stage aggregation through buffer batches (shuffle-shaped).
        src = source(SCHEMA, DATA, batches_per_partition=2)
        partial = HashAggregateExec(
            src, [("k", Ref(0, dt.STRING))],
            [AggSpec("s", Sum(Ref(1, dt.INT32))),
             AggSpec("a", Average(Ref(1, dt.INT32)))], mode="partial")
        bufschema = partial.buffer_schema
        final = HashAggregateExec(
            partial, [("k", Ref(0, dt.STRING))],
            [AggSpec("s", Sum(Ref(1, dt.INT32))),
             AggSpec("a", Average(Ref(1, dt.INT32)))], mode="final")
        # In final mode buffers are read positionally from the child's
        # buffer schema; the agg children only define types.
        dev = final.collect(device=True)
        keyf = lambda r: tuple((v is None, str(v)) for v in r)
        expected = [("a", 10, 10 / 3), ("b", 2, 2.0), (None, 12, 6.0),
                    ("c", 7, 7.0)]
        assert_rows_equal(sorted(dev, key=keyf), sorted(expected, key=keyf),
                          True, "partial+final vs oracle")

    def test_group_by_float_key_normalization(self):
        schema = [("k", dt.FLOAT64), ("v", dt.INT32)]
        data = {"k": [0.0, -0.0, float("nan"), float("nan"), 1.5],
                "v": [1, 2, 3, 4, 5]}
        plan = HashAggregateExec(
            source(schema, data), [("k", Ref(0, dt.FLOAT64))],
            [AggSpec("s", Sum(Ref(1, dt.INT32)))])
        dev = compare_engines(plan, sort_result=True)
        # -0.0 groups with 0.0; NaN groups with NaN => 3 groups.
        assert len(dev) == 3


class TestAggReviewRegressions:
    """Regressions for the ops-layer code-review findings."""

    def test_string_min_max(self):
        schema = [("k", dt.INT32), ("s", dt.STRING)]
        data = {"k": [1, 1, 1, 2, 2, 3],
                "s": ["banana", "apple", None, "zz", "aa", None]}
        plan = HashAggregateExec(
            source(schema, data, batches_per_partition=2),
            [("k", Ref(0, dt.INT32))],
            [AggSpec("mn", Min(Ref(1, dt.STRING))),
             AggSpec("mx", Max(Ref(1, dt.STRING)))])
        compare_engines(plan,
                        [(1, "apple", "banana"), (2, "aa", "zz"),
                         (3, None, None)], sort_result=True)

    def test_string_min_max_prefix_ties(self):
        schema = [("k", dt.INT32), ("s", dt.STRING)]
        data = {"k": [1, 1, 1], "s": ["ab", "abc", "a"]}
        plan = HashAggregateExec(
            source(schema, data), [("k", Ref(0, dt.INT32))],
            [AggSpec("mn", Min(Ref(1, dt.STRING))),
             AggSpec("mx", Max(Ref(1, dt.STRING)))])
        compare_engines(plan, [(1, "a", "abc")])

    def test_string_first_last(self):
        schema = [("k", dt.INT32), ("s", dt.STRING)]
        data = {"k": [1, 1, 2, 1], "s": ["x", None, "mid", "y"]}
        plan = HashAggregateExec(
            source(schema, data, batches_per_partition=2),
            [("k", Ref(0, dt.INT32))],
            [AggSpec("f", First(Ref(1, dt.STRING))),
             AggSpec("l", Last(Ref(1, dt.STRING)))])
        compare_engines(plan, [(1, "x", "y"), (2, "mid", "mid")],
                        sort_result=True)

    def test_partial_final_host_engine(self):
        # The host oracle must run real two-stage plans too.
        src = source(SCHEMA, DATA, batches_per_partition=2)
        partial = HashAggregateExec(
            src, [("k", Ref(0, dt.STRING))],
            [AggSpec("s", Sum(Ref(1, dt.INT32))),
             AggSpec("a", Average(Ref(1, dt.INT32))),
             AggSpec("f", First(Ref(1, dt.INT32)))], mode="partial")
        final = HashAggregateExec(
            partial, [("k", Ref(0, dt.STRING))],
            [AggSpec("s", Sum(Ref(1, dt.INT32))),
             AggSpec("a", Average(Ref(1, dt.INT32))),
             AggSpec("f", First(Ref(1, dt.INT32)))], mode="final")
        compare_engines(final,
                        [("a", 10, 10 / 3, 1), ("b", 2, 2.0, 2),
                         (None, 12, 6.0, 4), ("c", 7, 7.0, 7)],
                        approx_float=True, sort_result=True)

    def test_cast_date_trailing_garbage_null(self):
        from harness import check_expr
        from spark_rapids_tpu.columnar.host import HostBatch
        b = HostBatch.from_pydict(
            [("s", dt.STRING)],
            {"s": ["2020-01-01", "2020-01-01garbage", "2020-1-2", "2020",
                   "2020-13-01", None]})
        check_expr(E.Cast(Ref(0, dt.STRING), dt.DATE), b,
                   [18262, None, 18263, 18262, None, None])
