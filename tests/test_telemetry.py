"""Live telemetry plane (ISSUE 15): metric registry, OpenMetrics
exposition, scrape-able rejection telemetry, the persistent query event
log, and the post-hoc history CLI.

Contract under test:
- histogram quantiles reconstruct within the log-bucket error bound,
  and window rotation ages observations out of the quantile view while
  the lifetime ``_count``/``_sum`` pair stays monotonic;
- ``render_text`` emits OpenMetrics: ``# TYPE`` lines, escaped label
  values, counters with a ``_total`` sample suffix, ``# EOF``;
- metrics off (the default) records nothing and the recording API is a
  no-op;
- two concurrent tenant-tagged queries land in separate labeled series;
- a saturated admission queue produces a nonzero
  ``srt_queries_rejected_total{kind="queue-full"}`` scrape line and a
  structured QueryRejectedError;
- the localhost exporter serves ``/metrics`` over real HTTP;
- event-log records round-trip through ``scripts/history.py`` in a
  FRESH process (the history-server property), and a chaos run's
  recovery instants land in the record bit-identically to the flight
  recorder's ring.
"""

import json
import os
import subprocess
import sys
import threading
import urllib.request

import pytest

from spark_rapids_tpu import faults, monitoring
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.monitoring import exporter, history, telemetry
from spark_rapids_tpu.parallel import scheduler as SC
from spark_rapids_tpu.parallel.scheduler import QueryRejectedError

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_telemetry"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


@pytest.fixture(autouse=True)
def clean_state():
    faults.configure("")
    faults.reset_counters()
    telemetry.configure(False)
    telemetry.reset()
    yield
    telemetry.configure(False)
    telemetry.reset()
    monitoring.configure(False)
    monitoring.reset()
    exporter.stop()


def _session(**over):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.metrics.enabled", True)
    for k, v in over.items():
        s.set(k, v)
    return s


# ---------------------------------------------------------------------------
# Registry unit surface: histograms, exposition, kinds, no-op
# ---------------------------------------------------------------------------

def test_histogram_quantiles_within_bucket_error():
    telemetry.configure(True)
    for v in range(1, 1001):        # uniform 1..1000
        telemetry.observe("srt_t_lat_ms", float(v))
    snap = telemetry.snapshot()["metrics"]["srt_t_lat_ms"]["series"][0]
    assert snap["count"] == 1000
    assert snap["sum"] == pytest.approx(500500.0)
    # Log buckets grow ~19% per step: a reconstructed quantile lands
    # within ~one bucket of the true value.
    assert snap["p50"] == pytest.approx(500.0, rel=0.20)
    assert snap["p99"] == pytest.approx(990.0, rel=0.20)
    # Non-positive observations clamp to the zero bucket, not a crash.
    telemetry.observe("srt_t_zero_ms", 0.0)
    z = telemetry.snapshot()["metrics"]["srt_t_zero_ms"]["series"][0]
    assert z["p50"] == 0.0 and z["count"] == 1


def test_histogram_window_rotation_ages_out_quantiles():
    telemetry.configure(True)
    for _ in range(100):
        telemetry.observe("srt_t_rot_ms", 1000.0)
    # Push the 1000ms epoch past the window (current + 7 retained).
    for _ in range(8):
        telemetry.rotate_windows()
    for _ in range(3):
        telemetry.observe("srt_t_rot_ms", 10.0)
    s = telemetry.snapshot()["metrics"]["srt_t_rot_ms"]["series"][0]
    # Quantiles see only the live window; lifetime count/sum keep all.
    assert s["p50"] == pytest.approx(10.0, rel=0.25)
    assert s["p99"] == pytest.approx(10.0, rel=0.25)
    assert s["count"] == 103
    assert s["sum"] == pytest.approx(100030.0)


def test_openmetrics_rendering_golden():
    telemetry.configure(True)
    telemetry.inc("srt_t_requests", tenant='a"b\\c\nd')
    telemetry.inc("srt_t_requests", amount=2.0, tenant="plain")
    telemetry.set_gauge("srt_t_depth", 7)
    telemetry.observe("srt_t_ms", 100.0)
    text = telemetry.render_text()
    assert "# TYPE srt_t_requests counter" in text
    assert "# TYPE srt_t_depth gauge" in text
    assert "# TYPE srt_t_ms histogram" in text
    # Counter samples wear the _total suffix; label escaping is the
    # OpenMetrics triple (backslash, quote, newline).
    assert 'srt_t_requests_total{tenant="a\\"b\\\\c\\nd"} 1' in text
    assert 'srt_t_requests_total{tenant="plain"} 2' in text
    assert "srt_t_depth 7" in text
    assert 'srt_t_ms{quantile="0.5"}' in text
    assert "srt_t_ms_sum 100" in text
    assert "srt_t_ms_count 1" in text
    assert text.endswith("# EOF\n")


def test_openmetrics_overload_plane_series_golden():
    """The overload-survival series (ISSUE 18) scrape as first-class
    OpenMetrics: preemptions and client retries as counters (_total
    suffix, class/kind labels), pressure score and brownout state as
    gauges."""
    telemetry.configure(True)
    telemetry.inc("srt_preemptions", **{"class": "background"})
    telemetry.set_gauge("srt_pressure_score", 0.42)
    telemetry.set_gauge("srt_brownout_active", 1)
    telemetry.inc("srt_client_retries", kind="queue-full")
    telemetry.inc("srt_client_retries", kind="brownout")
    text = telemetry.render_text()
    assert "# TYPE srt_preemptions counter" in text
    assert "# TYPE srt_pressure_score gauge" in text
    assert "# TYPE srt_brownout_active gauge" in text
    assert "# TYPE srt_client_retries counter" in text
    assert 'srt_preemptions_total{class="background"} 1' in text
    assert "srt_pressure_score 0.42" in text
    assert "srt_brownout_active 1" in text
    assert 'srt_client_retries_total{kind="queue-full"} 1' in text
    assert 'srt_client_retries_total{kind="brownout"} 1' in text


def test_metric_kind_is_sticky():
    telemetry.configure(True)
    telemetry.inc("srt_t_kind")
    with pytest.raises(ValueError):
        telemetry.set_gauge("srt_t_kind", 1.0)


def test_metrics_off_records_nothing():
    assert not telemetry.enabled()
    telemetry.inc("srt_t_off")
    telemetry.observe("srt_t_off_ms", 5.0)
    telemetry.set_gauge("srt_t_off_g", 1.0)
    assert telemetry.snapshot()["metrics"] == {}


# ---------------------------------------------------------------------------
# Query instrumentation: tenants, rejections, scrape
# ---------------------------------------------------------------------------

def _series(name):
    m = telemetry.snapshot()["metrics"].get(name, {"series": []})
    return {tuple(sorted(s["labels"].items())): s for s in m["series"]}


def test_per_tenant_series_isolation_two_concurrent_queries():
    s = _session()
    df_a = s.range(0, 20_000)
    df_b = s.range(0, 30_000)
    ha = df_a.submit(tenant="tenantA")
    hb = df_b.submit(tenant="tenantB")
    assert len(ha.result(120)) == 20_000
    assert len(hb.result(120)) == 30_000
    q = _series("srt_queries")
    key_a = (("class", "-"), ("status", "ok"), ("tenant", "tenantA"))
    key_b = (("class", "-"), ("status", "ok"), ("tenant", "tenantB"))
    assert q[key_a]["value"] == 1.0
    assert q[key_b]["value"] == 1.0
    lat = _series("srt_query_latency_ms")
    assert lat[(("class", "-"), ("tenant", "tenantA"))]["count"] == 1
    assert lat[(("class", "-"), ("tenant", "tenantB"))]["count"] == 1


def test_queue_full_rejection_scrape_line():
    s = _session(**{
        "spark.rapids.sql.scheduler.maxConcurrentQueries": 1,
        "spark.rapids.sql.scheduler.queueDepth": 0,
        "spark.rapids.sql.scheduler.admissionTimeoutMs": 200,
    })
    df = s.range(0, 1000)
    mgr = SC.get_query_manager(s.conf)
    hog = mgr.admit()
    try:
        with pytest.raises(QueryRejectedError) as ei:
            df.collect()
    finally:
        mgr.finish(hog)
    # Structured shed-load fields on the error itself...
    assert ei.value.kind == "queue-full"
    assert ei.value.queue_depth is not None
    # ...and as a labeled scrape series with the kind dimension.
    text = telemetry.render_text()
    line = next(ln for ln in text.splitlines()
                if ln.startswith("srt_queries_rejected_total")
                and 'kind="queue-full"' in ln)
    assert float(line.rsplit(" ", 1)[1]) >= 1
    assert "# TYPE srt_queries_rejected counter" in text
    # The rejected query never admitted: it must not count as run.
    assert not any('status="ok"' in ln and "srt_queries_total" in ln
                   for ln in text.splitlines())


def test_exporter_serves_metrics_over_http():
    telemetry.configure(True)
    telemetry.inc("srt_t_http_hits", amount=3.0)
    port = exporter.ensure_started(0)
    assert port > 0 and exporter.running()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        body = r.read().decode()
        ctype = r.headers.get("Content-Type", "")
    assert "text/plain" in ctype
    assert "srt_t_http_hits_total 3" in body
    assert body.endswith("# EOF\n")
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
        assert r.status == 200
    exporter.stop()
    assert not exporter.running()


def test_funnel_sync_reconciles_with_scheduler_counters():
    s = _session()
    base = SC.counters().get("admitted", 0)
    s.range(0, 5000).collect()
    s.range(0, 5000).collect()
    q = _series("srt_scheduler_admitted")
    total = sum(v["value"] for v in q.values())
    assert total == SC.counters().get("admitted", 0) >= base + 2
    # Idempotent: a second sync publishes the same absolutes.
    assert _series("srt_scheduler_admitted") == q


# ---------------------------------------------------------------------------
# Event log + history CLI
# ---------------------------------------------------------------------------

def _history_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "history.py"),
         *args],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=120)


def test_event_log_roundtrip_through_history_cli(tmp_path):
    log_dir = str(tmp_path / "events")
    s = _session(**{
        "spark.rapids.sql.eventLog.dir": log_dir,
        "spark.rapids.sql.trace.enabled": True,
    })
    s.range(0, 10_000).collect(tenant="cliTenant")
    s.range(0, 4_000).collect()
    records = history.read_events(log_dir)
    assert len(records) == 2
    rec = records[0]
    assert rec["v"] == history.SCHEMA_VERSION
    assert rec["status"] == "ok" and rec["tenant"] == "cliTenant"
    assert rec["nodes"][0]["name"] == "RangeExec"
    assert rec["categories"]          # trace was on: span breakdown
    # The CLI reconstructs the reports in a FRESH process, from the log
    # alone (the writer process's state is irrelevant by then).
    ls = _history_cli(log_dir)
    assert ls.returncode == 0, ls.stderr
    assert ls.stdout.count("query ") == 2
    assert "tenant=cliTenant" in ls.stdout
    rep = _history_cli(log_dir, "--query", str(rec["query_id"]))
    assert rep.returncode == 0, rep.stderr
    assert "RangeExec" in rep.stdout
    assert f"query {rec['query_id']} [ok]" in rep.stdout
    summ = _history_cli(log_dir, "--summary")
    assert summ.returncode == 0, summ.stderr
    fleet = json.loads(summ.stdout)
    assert fleet["queries"] == 2
    assert fleet["byStatus"] == {"ok": 2}
    assert fleet["byTenant"].get("cliTenant") == 1
    assert fleet["p50Ms"] is not None


def test_event_log_off_writes_nothing(tmp_path):
    s = _session()            # metrics on, event log NOT configured
    s.range(0, 1000).collect()
    assert history.log_dir() == ""
    assert list(tmp_path.iterdir()) == []


def test_chaos_instants_bit_identical_in_event_log(data_dir, tmp_path):
    log_dir = str(tmp_path / "events")
    s = _session(**{
        "spark.rapids.sql.eventLog.dir": log_dir,
        "spark.rapids.sql.trace.enabled": True,
        "spark.rapids.sql.test.faults": "oom@upload:1,transient@download:1",
        "spark.rapids.sql.test.faults.seed": 7,
        "spark.rapids.sql.retry.backoffMs": 1,
        "spark.rapids.sql.format.scanCache.maxBytes": 0,
    })
    df = tpch.QUERIES["q3"](s, data_dir)
    df.collect()
    qid = df._physical().last_ctx.cache["trace_query"]
    (rec,) = history.read_events(log_dir)
    # The record's instants are the ring's instants, verbatim (JSON
    # round-tripped): recovery forensics survive the process.
    want = json.loads(json.dumps(
        [[e[1], e[2], e[3], history._json_safe(e[7])]
         for e in monitoring.events(qid) if e[0] == "i"]))
    assert rec["instants"] == want
    names = {i[0] for i in rec["instants"]}
    assert "fault-injected" in names
    kinds = {(i[3] or {}).get("kind") for i in rec["instants"]
             if i[0] == "fault-injected"}
    assert {"oom", "transient"} <= kinds
    assert rec["status"] == "ok"      # ladder recovered; record agrees
