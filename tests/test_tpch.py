"""TPC-H q1/q6/q3/q5 end-to-end: device engine vs host oracle vs pandas.

The integration-test analog of the reference's tpch_test.py (which asserts
GPU==CPU per query via assert_gpu_and_cpu_are_equal_collect)."""

import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


def _session():
    s = TpuSession()
    # Float sums vary with evaluation order on any parallel engine; the
    # reference gates them behind variableFloatAgg — enable like the
    # integration tests do (approximate_float marker analog).
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    return s


@pytest.mark.parametrize("qname", ["q1", "q6", "q3", "q5"])
def test_query_device_matches_pandas(qname, data_dir):
    df = tpch.QUERIES[qname](_session(), data_dir)
    got = df.collect()
    want = tpch.pandas_query(qname, data_dir)
    assert tpch.check_result(qname, got, want), (got, want)


@pytest.mark.parametrize("qname", ["q1", "q6"])
def test_query_device_matches_host_engine(qname, data_dir):
    df = tpch.QUERIES[qname](_session(), data_dir)
    got = df.collect()
    want = df.collect_host()
    assert tpch.rows_close(got, want), (got, want)


def test_pruned_scan_schema(data_dir):
    """Column pruning narrows the lineitem scan to referenced columns."""
    from spark_rapids_tpu.plan.pruning import prune_columns
    from spark_rapids_tpu.plan import logical as L
    df = tpch.q6(_session(), data_dir)
    pruned = prune_columns(df._plan)

    def find_scan(p):
        if isinstance(p, L.FileScan):
            return p
        for c in p.children:
            s = find_scan(c)
            if s is not None:
                return s
        return None

    scan = find_scan(pruned)
    names = [n for n, _ in scan.source_schema]
    assert set(names) == {"l_shipdate", "l_discount", "l_quantity",
                          "l_extendedprice"}
