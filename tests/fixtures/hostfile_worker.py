"""Worker-process half of the cross-process hostfile-transport test.

Run as a standalone python process (NOT under the test's jax config):
opens the shared spool directory as one independent worker, map-writes
its deterministic slice of a two-column table as shards for every
reduce partition, commits its manifest, and (when given a rendezvous
address) announces the commit over the socket. The parent test process
then reduce-fetches both workers' shards and asserts the union is
bit-identical to the expected table — the DCN multi-slice stand-in
demonstrated with real process isolation.

Usage:
    python hostfile_worker.py <spool_dir> <tag> <worker_id> \
        <num_partitions> <rendezvous host:port | ->
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# Runs as a bare script from anywhere: the repo root (two levels up)
# must be importable exactly like the parent test process sees it.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def worker_rows(worker_id: str, partition: int):
    """Deterministic (key, value) rows this worker contributes to one
    reduce partition — pure function of (worker, partition) so the
    parent can compute the expected union without any IPC."""
    w = int(worker_id[1:])          # "w0" -> 0
    keys = [partition * 100 + w * 10 + i for i in range(5)]
    vals = [k * 3 + 1 for k in keys]
    return keys, vals


def main() -> int:
    spool, tag, worker_id, n_parts_s, rv = sys.argv[1:6]
    n_parts = int(n_parts_s)

    import numpy as np

    from spark_rapids_tpu import config as C
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.host import (HostBatch, HostColumn,
                                                host_to_device)
    from spark_rapids_tpu.parallel.transport.hostfile import \
        HostFileTransport

    conf = C.TpuConf({
        C.SHUFFLE_TRANSPORT_HOSTFILE_DIR.key: spool,
        C.SHUFFLE_TRANSPORT_HOSTFILE_WORKER_ID.key: worker_id,
        C.SHUFFLE_TRANSPORT_HOSTFILE_RENDEZVOUS.key:
            "" if rv == "-" else rv,
    })
    sess = HostFileTransport().open(conf, tag, n_parts)
    for p in range(n_parts):
        keys, vals = worker_rows(worker_id, p)
        hb = HostBatch(
            ("k", "v"),
            [HostColumn(dt.INT64, np.asarray(keys, np.int64),
                        np.ones(len(keys), bool)),
             HostColumn(dt.INT64, np.asarray(vals, np.int64),
                        np.ones(len(vals), bool))])
        sess.write_shard(p, host_to_device(hb))
    sess.commit()
    print(f"worker {worker_id} committed {n_parts} partitions")
    return 0


if __name__ == "__main__":
    sys.exit(main())
