"""Overload survival plane (ISSUE 18): memory-pressure shedding,
brownout admission, client backpressure convergence, and the mixed-
tenant step-load soak.

The contracts under test:

- ``stores.pressure_score`` blends the catalog watermarks with the
  device fraction dominant (it is what OOMs), clamped per tier.
- The brownout state machine (QueryManager.note_pressure) flips ON only
  after the enter score is SUSTAINED for brownout.sustainMs, stays on
  through the hysteresis band, and flips OFF below the exit score; the
  default-off gate never flips at all.
- During brownout, BACKGROUND admissions shed with kind="brownout" and
  a retry hint while interactive/batch still admit.
- ``collect_with_retry`` is the obedient client: it honors
  ``retry_after_ms`` with capped deterministic-jitter backoff, re-raises
  hintless rejections immediately, gives up after maxAttempts — and a
  herd of such clients converges end to end.
- Cluster placement demotes a pressured worker (CBEAT telemetry
  piggyback -> _pick_locked) below steal-delay preference so it sheds
  NEW stages to its peers.
- Step-load soak: a 4x background step spike, with preemption + retry
  enabled, keeps interactive latency bounded, keeps background making
  forward progress, and returns only byte-correct rows.
"""

import base64
import json
import threading
import time
import types

import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.memory import oom, stores
from spark_rapids_tpu.parallel import cluster as CL
from spark_rapids_tpu.parallel import qos as Q
from spark_rapids_tpu.parallel import scheduler as SC
from spark_rapids_tpu.parallel.cluster import coordinator as CO
from spark_rapids_tpu.parallel.scheduler import (
    QueryManager, QueryRejectedError)


@pytest.fixture(autouse=True)
def clean_state():
    faults.configure("")
    faults.reset_counters()
    SC.reset_counters()
    Q.reset_counters()
    oom.reset_degradation()
    # The process-global device semaphore is sized by the FIRST collect
    # in the process; drop it so the soak's concurrentTpuTasks=1 sizes
    # a fresh gate (a wider inherited gate removes the contention the
    # step-load assertions depend on).
    with stores._GLOBAL_SEM_LOCK:
        stores._GLOBAL_SEM = None
    yield
    faults.configure("")
    faults.reset_counters()
    SC.reset_counters()
    Q.reset_counters()
    oom.reset_degradation()
    stores._PREEMPT_ENABLED = False
    with stores._GLOBAL_SEM_LOCK:
        stores._GLOBAL_SEM = None
    # Tests here rebuild the process-wide manager in QoS mode and at
    # odd sizes; drop it so later modules start from the default.
    with SC._MANAGER_LOCK:
        SC._MANAGER = None


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_overload"))
    tpch.generate(d, scale=0.02, files_per_table=10, seed=11)
    return d


# ---------------------------------------------------------------------------
# Pressure score
# ---------------------------------------------------------------------------

def _cat(dev, host=0, disk=0, dev_budget=100, host_budget=100):
    return types.SimpleNamespace(
        device_bytes=dev, device_budget=dev_budget,
        host_bytes=host, host_budget=host_budget, disk_bytes=disk)


def test_pressure_score_blend_and_clamp():
    assert stores.pressure_score(None) == 0.0
    assert stores.pressure_score(_cat(0)) == 0.0
    assert stores.pressure_score(_cat(50)) == 0.5
    # Device dominates; host and disk add the smaller terms.
    assert stores.pressure_score(_cat(50, 40, 20)) == pytest.approx(
        0.5 + 0.25 * 0.4 + 0.1 * 0.2)
    # Each tier fraction clamps at 1 — a ladder deep into disk spill
    # reads hotter than merely-full but stays bounded.
    assert stores.pressure_score(_cat(500, 500, 500)) == pytest.approx(
        1.35)


# ---------------------------------------------------------------------------
# Brownout state machine
# ---------------------------------------------------------------------------

def _pressure_conf(sustain_ms=40, enter=0.9, exit_=0.7, enabled=True):
    s = TpuSession()
    if enabled:
        s.set("spark.rapids.sql.scheduler.pressure.enabled", True)
    s.set("spark.rapids.sql.scheduler.pressure.brownout.sustainMs",
          sustain_ms)
    s.set("spark.rapids.sql.scheduler.pressure.brownout.enterScore",
          enter)
    s.set("spark.rapids.sql.scheduler.pressure.brownout.exitScore",
          exit_)
    return s.conf


def test_brownout_enters_after_sustain_exits_below_floor():
    mgr = QueryManager(max_concurrent=2)
    conf = _pressure_conf(sustain_ms=40)
    mgr.note_pressure(0.95, conf)
    assert not mgr.brownout_active          # spike, not yet sustained
    time.sleep(0.06)
    mgr.note_pressure(0.95, conf)
    assert mgr.brownout_active
    assert SC.counters().get("brownouts", 0) == 1
    # Hysteresis band: below enter but above exit keeps it on.
    mgr.note_pressure(0.8, conf)
    assert mgr.brownout_active
    mgr.note_pressure(0.5, conf)
    assert not mgr.brownout_active
    assert SC.counters().get("brownoutExits", 0) == 1


def test_brownout_requires_sustained_pressure():
    """A transient spike above the enter score never flips the gate —
    the sustain window is what separates a hot partition from real
    overload."""
    mgr = QueryManager(max_concurrent=2)
    conf = _pressure_conf(sustain_ms=60000)
    mgr.note_pressure(0.99, conf)
    mgr.note_pressure(0.99, conf)
    assert not mgr.brownout_active
    # Dropping below enter resets the sustain clock entirely.
    mgr.note_pressure(0.1, conf)
    assert mgr._pressure_high_since is None


def test_brownout_gate_off_by_default():
    mgr = QueryManager(max_concurrent=2)
    conf = _pressure_conf(sustain_ms=0, enabled=False)
    mgr.note_pressure(0.99, conf)
    mgr.note_pressure(0.99, conf)
    assert not mgr.brownout_active
    mgr.note_pressure(0.99, None)           # no conf at all: no-op
    assert not mgr.brownout_active
    assert SC.counters().get("brownouts", 0) == 0


def test_brownout_sheds_background_admits_interactive():
    """During brownout, background admissions reject with
    kind="brownout" and a retry hint; interactive and batch admit."""
    mgr = QueryManager(max_concurrent=2, queue_depth=4,
                       admission_timeout_ms=2000,
                       qos=Q.QosPolicy("8,3,1", 8))
    mgr.brownout_active = True
    mgr._pressure_score = 0.93
    with pytest.raises(QueryRejectedError, match="brownout") as ei:
        mgr.admit(priority="background")
    assert ei.value.kind == "brownout"
    assert ei.value.retry_after_ms is not None
    assert ei.value.retry_after_ms > 0
    t_i = mgr.admit(priority="interactive")
    t_b = mgr.admit(priority="batch")
    mgr.finish(t_i)
    mgr.finish(t_b)
    assert Q.counters().get("rejected.brownout", 0) >= 1
    # Gate lifted: background admits again.
    mgr.brownout_active = False
    t_bg = mgr.admit(priority="background")
    mgr.finish(t_bg)


# ---------------------------------------------------------------------------
# Client backpressure: backoff_ms + collect_with_retry
# ---------------------------------------------------------------------------

def test_backoff_ms_deterministic_jittered_capped():
    # Exact replay: same (hint, attempt, seed) -> same delay.
    assert SC.backoff_ms(100.0, 1, 3, 10000.0) == \
        SC.backoff_ms(100.0, 1, 3, 10000.0)
    # Jitter stretches the hint by [0, 25%), never shrinks it.
    for seed in range(16):
        d = SC.backoff_ms(100.0, 1, seed, 10000.0)
        assert 100.0 <= d < 125.0
    # Different clients spread out (not all identical).
    delays = {SC.backoff_ms(100.0, 1, seed, 10000.0)
              for seed in range(16)}
    assert len(delays) > 1
    # The cap wins over any hint.
    assert SC.backoff_ms(100000.0, 1, 0, 500.0) == 500.0
    # A missing/zero hint falls back to the 250ms prior.
    assert 250.0 <= SC.backoff_ms(None, 1, 0, 10000.0) < 312.5
    assert 250.0 <= SC.backoff_ms(0.0, 1, 0, 10000.0) < 312.5


def _rejector(fail_times, hint=20.0, kind="queue-full"):
    """attempt_fn failing ``fail_times`` times then returning rows."""
    state = {"n": 0}

    def fn():
        state["n"] += 1
        if state["n"] <= fail_times:
            raise QueryRejectedError("shed", kind=kind, queue_depth=1,
                                     retry_after_ms=hint)
        return [("ok",)]

    return fn, state


def test_collect_with_retry_honors_hint():
    slept = []
    fn, state = _rejector(2, hint=20.0)
    rows = SC.collect_with_retry(fn, max_attempts=5, max_backoff_ms=1e4,
                                 seed=7, sleep=slept.append)
    assert rows == [("ok",)]
    assert state["n"] == 3
    assert slept == [SC.backoff_ms(20.0, 1, 7, 1e4) / 1000.0,
                     SC.backoff_ms(20.0, 2, 7, 1e4) / 1000.0]
    assert SC.counters().get("clientRetries", 0) == 2
    assert SC.counters().get("clientRetries.queue-full", 0) == 2


def test_collect_with_retry_reraises_hintless():
    """No hint means retrying as-is can never help (deadline-unmeetable
    by raw cost): re-raise immediately, zero sleeps."""
    slept = []
    fn, state = _rejector(5, hint=None, kind="deadline-unmeetable")
    with pytest.raises(QueryRejectedError):
        SC.collect_with_retry(fn, max_attempts=5, max_backoff_ms=1e4,
                              sleep=slept.append)
    assert state["n"] == 1
    assert slept == []
    assert SC.counters().get("clientRetries", 0) == 0


def test_collect_with_retry_exhausts_attempts():
    slept = []
    fn, state = _rejector(100, hint=10.0)
    with pytest.raises(QueryRejectedError):
        SC.collect_with_retry(fn, max_attempts=3, max_backoff_ms=1e4,
                              sleep=slept.append)
    assert state["n"] == 3
    assert len(slept) == 2


def test_collect_with_retry_defaults_from_conf():
    s = TpuSession()
    s.set("spark.rapids.sql.client.retry.maxAttempts", 2)
    slept = []
    fn, state = _rejector(100, hint=10.0)
    with pytest.raises(QueryRejectedError):
        SC.collect_with_retry(fn, conf=s.conf, sleep=slept.append)
    assert state["n"] == 2


def test_collect_with_retry_converges_e2e(data_dir):
    """A rejected-then-retried collect lands once the slot frees: the
    client converges onto the scheduler's service rate instead of
    erroring out."""
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.scheduler.maxConcurrentQueries", 1)
    s.set("spark.rapids.sql.scheduler.queueDepth", 0)
    s.set("spark.rapids.sql.scheduler.admissionTimeoutMs", 2000)
    df = tpch.QUERIES["q6"](s, data_dir)
    want = df.collect()
    mgr = SC.get_query_manager(s.conf)
    hog = mgr.admit()
    releaser = threading.Timer(0.25, mgr.finish, args=(hog,))
    releaser.daemon = True
    releaser.start()
    try:
        got = df.collect_with_retry(max_attempts=10, seed=3)
    finally:
        releaser.join(10)
    assert got == want
    assert SC.counters().get("clientRetries", 0) >= 1


# ---------------------------------------------------------------------------
# Cluster placement demotion (CBEAT pressure piggyback -> _pick_locked)
# ---------------------------------------------------------------------------

def test_cbeat_telemetry_carries_pressure_score():
    conf = TpuSession().conf
    co = CL.get_coordinator(conf)
    try:
        co.dispatch(["CREG", "wA"])
        blob = base64.b64encode(json.dumps(
            {"series": {"srt_pressure_score|": 0.91},
             "kinds": {}}).encode()).decode()
        co.dispatch(["CBEAT", "wA", blob])
        assert co.workers["wA"].pressure == pytest.approx(0.91)
    finally:
        CL.shutdown_coordinator()


def test_pick_demotes_pressured_worker():
    """A worker at/past shedScore loses both the steal-delay
    reservation and the pick to an unpressured peer — even for a stage
    it rendezvous-owns — so it sheds NEW stages instead of spilling
    under them."""
    s = TpuSession()
    s.set("spark.rapids.sql.scheduler.pressure.enabled", True)
    s.set("spark.rapids.sql.scheduler.pressure.shedScore", 0.75)
    conf = s.conf
    co = CL.get_coordinator(conf)
    try:
        sid = next(n for n in range(1, 50)
                   if CO._hrw_owner(["wA", "wB"], n) == "wA")
        q = CO.QueryRun(co, 96, conf, {sid: CO._StageTask(sid, set())},
                        {})
        with co._lock:
            co.queries[96] = q
            co._touch_locked("wA")
            co._touch_locked("wB")
            co.workers["wA"].pressure = 0.9
            assert q._pick_locked("wA") is None     # shed to the peer
            _, picked = q._pick_locked("wB")
            assert picked.sid == sid and picked.worker == "wB"
            co.queries.pop(96)
    finally:
        CL.shutdown_coordinator()


def test_pick_all_pressured_collapses_to_old_order():
    """All-pressured (or gate off) collapses the demotion tier to a
    constant: placement is exactly the old (locality, affinity) order —
    work conservation never deadlocks on pressure."""
    s = TpuSession()
    s.set("spark.rapids.sql.scheduler.pressure.enabled", True)
    s.set("spark.rapids.sql.scheduler.pressure.shedScore", 0.75)
    conf = s.conf
    co = CL.get_coordinator(conf)
    try:
        sid = next(n for n in range(1, 50)
                   if CO._hrw_owner(["wA", "wB"], n) == "wA")
        q = CO.QueryRun(co, 95, conf, {sid: CO._StageTask(sid, set())},
                        {})
        with co._lock:
            co.queries[95] = q
            co._touch_locked("wA")
            co._touch_locked("wB")
            co.workers["wA"].pressure = 0.9
            co.workers["wB"].pressure = 0.95
            _, picked = q._pick_locked("wA")        # owner keeps it
            assert picked.sid == sid and picked.worker == "wA"
            co.queries.pop(95)
    finally:
        CL.shutdown_coordinator()


# ---------------------------------------------------------------------------
# Step-load soak
# ---------------------------------------------------------------------------

def _soak_session():
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    # Device-rooted plans only — host-rooted roots never touch the
    # device gate, so nothing would ever preempt.
    s.set("spark.rapids.sql.cost.enabled", False)
    # Admission admits the whole mixed fleet; the CLASS-RANKED DEVICE
    # GATE (concurrentTpuTasks=1 below) is what orders the spike — an
    # interactive arrival preempts the background holder there.
    s.set("spark.rapids.sql.scheduler.maxConcurrentQueries", 6)
    s.set("spark.rapids.sql.scheduler.queueDepth", 4)
    s.set("spark.rapids.sql.scheduler.qos.enabled", True)
    s.set("spark.rapids.sql.scheduler.preemption.enabled", True)
    s.set("spark.rapids.sql.concurrentTpuTasks", 1)
    return s


@pytest.mark.slow
def test_step_load_soak(data_dir):
    """Mixed-tenant step-load: a 4x background step spike lands on a
    steady interactive client. Interactive latency stays bounded
    (preemption keeps the device from being held hostage), background
    keeps making forward progress through shed/retry (no starvation,
    no errors), and every row returned on both sides is byte-correct.

    Slow-marked like the qos soak: the ``step-load-soak`` tier-1 matrix
    entry runs this module without the marker filter every CI run."""
    want = tpch.QUERIES["q1"](_soak_session(), data_dir).collect()

    # Unloaded interactive latency profile (after the warmup above).
    unloaded = []
    for _ in range(4):
        t0 = time.perf_counter()
        got = tpch.QUERIES["q1"](_soak_session(), data_dir) \
            .collect(priority="interactive")
        unloaded.append(time.perf_counter() - t0)
        assert got == want
    SC.reset_counters()

    # THE STEP: 4 sustained background clients arrive at once.
    stop = threading.Event()
    bg_done = []
    bg_bad = []
    bg_errors = []

    def bg_client(k):
        df = tpch.QUERIES["q1"](_soak_session(), data_dir)
        while not stop.is_set():
            try:
                rows = df.collect_with_retry(
                    priority="background", tenant=f"t{k % 2}",
                    max_attempts=50, max_backoff_ms=500.0, seed=k)
            except QueryRejectedError:
                continue        # shed through max attempts: back off
            except Exception as e:              # pragma: no cover
                bg_errors.append(e)
                return
            if rows != want:
                bg_bad.append(k)
                return
            bg_done.append(k)

    threads = [threading.Thread(target=bg_client, args=(k,), daemon=True)
               for k in range(4)]
    for t in threads:
        t.start()

    # Interactive client rides through the spike.
    loaded = []
    try:
        for i in range(4):
            t0 = time.perf_counter()
            got = tpch.QUERIES["q1"](_soak_session(), data_dir) \
                .collect_with_retry(priority="interactive",
                                    tenant="fg", seed=100 + i)
            loaded.append(time.perf_counter() - t0)
            assert got == want, "interactive rows diverged under load"
    finally:
        stop.set()
        for t in threads:
            t.join(120)

    assert not bg_errors, bg_errors
    assert not bg_bad, "background rows diverged under load"
    # Graceful degradation = forward progress, not a fixed rate.
    assert len(bg_done) >= 1, "background starved outright"

    ctrs = SC.counters()
    assert ctrs.get("preemptions", 0) >= 1, \
        "the spike never exercised class preemption"

    # Interactive latency bound: p99 (max of the window) within 2x the
    # unloaded profile, plus a small absolute floor for scheduler
    # jitter at CI data scale (sub-second queries).
    unloaded_p99 = max(unloaded)
    loaded_p99 = max(loaded)
    assert loaded_p99 <= 2.0 * unloaded_p99 + 0.75, \
        (f"interactive p99 {loaded_p99:.2f}s vs unloaded "
         f"{unloaded_p99:.2f}s x2 under the step spike "
         f"(preemptions={ctrs.get('preemptions')}, "
         f"clientRetries={ctrs.get('clientRetries', 0)})")
