"""Suite-query parity: every benchmarks/suites.py query (TPC-DS- and
TPCxBB-like) matches its pandas oracle at a small scale factor."""

import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import suites


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("suites_small")
    suites.generate(str(d), scale=0.01, files_per_table=2)
    return str(d)


@pytest.mark.parametrize("qn", sorted(suites.QUERIES))
def test_suite_query_matches_pandas(qn, data_dir):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.hasNans", False)
    # Device-vs-pandas parity: pin the device plan (the cost model would
    # host-place these mini-scale inputs, testing the oracle against
    # itself).
    s.set("spark.rapids.sql.cost.enabled", False)
    got = suites.QUERIES[qn](s, data_dir).collect()
    want = suites.pandas_query(qn, data_dir)
    assert suites.check_result(qn, got, want), (
        f"{qn}: device diverges\n got[:3]={got[:3]}\nwant[:3]={want[:3]}")
