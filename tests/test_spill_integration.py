"""Engine-integrated spill: a real DataFrame query under a deliberately
tiny device budget completes correctly BY spilling shuffle buckets
(VERDICT r1 item 3; ref: RapidsCachingWriter inserting shuffle buffers
into the spillable device store, RapidsShuffleInternalManager.scala:57)."""

import numpy as np

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.ops.base import ExecContext
from spark_rapids_tpu.plan.logical import agg_count, agg_sum, col


def _df(session, n=4000, parts=4):
    rng = np.random.default_rng(3)
    return session.create_dataframe(
        {"k": rng.integers(0, 50, n).tolist(),
         "v": rng.integers(0, 1000, n).tolist()},
        [("k", dt.INT64), ("v", dt.INT64)], num_partitions=parts)


def test_groupby_spills_and_stays_correct():
    s = TpuSession()
    # ~8 KiB budget: below even one exchange's bucket total, so buckets
    # spill host-ward DURING materialization and must restore on read.
    s.set("spark.rapids.memory.tpu.budgetBytes", 8 * 1024)
    # This asserts the IN-PROCESS transport's map-side spill behavior
    # (hostfile map shards live in spool files, not the catalog), so
    # pin the transport against the SRT_SHUFFLE_TRANSPORT matrix env.
    s.set("spark.rapids.sql.shuffle.transport", "inprocess")
    q = _df(s).group_by("k").agg(agg_sum(col("v")).alias("sv"),
                                 agg_count().alias("n")).order_by("k")
    phys = q._physical()
    ctx = ExecContext(phys.conf)
    got = phys.root.collect(ctx, device=True)
    spills = ctx.catalog.metrics["spill_to_host"]
    restores = ctx.catalog.metrics["restore_from_host"]
    ctx.close()
    assert spills > 0, "tiny budget must force shuffle-bucket spills"
    assert restores > 0
    assert got == q.collect_host()


def test_no_raw_batches_in_cache():
    """ctx.cache holds transport sessions whose shards are spillable
    handles, not pinned device batches."""
    from spark_rapids_tpu.memory.stores import SpillableBatch
    from spark_rapids_tpu.parallel.transport.base import ShuffleSession
    s = TpuSession()
    q = _df(s).group_by("k").agg(agg_count().alias("n"))
    phys = q._physical()
    ctx = ExecContext(phys.conf)
    phys.root.collect(ctx, device=True)
    seen = 0
    for key, val in ctx.cache.items():
        if key.startswith("shuffle:") and not key.endswith(":rows"):
            assert isinstance(val, ShuffleSession), \
                f"raw materialization hoarded in {key}"
            seen += 1
            for bucket in getattr(val, "buckets", []):
                for item in bucket:
                    assert isinstance(item, SpillableBatch), \
                        f"raw batch hoarded in {key}"
    assert seen >= 1
    ctx.close()
