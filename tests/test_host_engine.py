"""Vectorized host engine: forced-host parity sweep + null propagation.

The host (numpy) engine must be bit-identical (within the float
tolerance of benchmarks/compare.py) to BOTH oracles on the 11-query
bench suite:

- the device engine (``collect()``), the dual-engine invariant every
  expression/op pair already promises at unit scale, exercised here
  end-to-end through sort/aggregate/join/window's vectorized host
  halves;
- the pandas implementation of the same query, the independent
  cross-check that a shared host/device bug can't hide behind.

q1/q6 run in tier-1 (scan+filter+agg covers the fused project/filter
closures and the segmented aggregate); the rest of the sweep is
slow-marked for the host-engine CI matrix entry.

Also here: the per-expression-family null-propagation audit for the
shared all-valid mask helper (columnar/host.py all_valid) — nulls must
flow through the vectorized kernels exactly as through the device path.
"""

import numpy as np
import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import suites, tpch
from spark_rapids_tpu.benchmarks.compare import (compare_results,
                                                 first_mismatch)
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch, all_valid
from spark_rapids_tpu import exprs as E
from spark_rapids_tpu.exprs.base import BoundReference as Ref, lit

# The 11-query host-engine sweep: the five BASELINE.md target configs
# (q1/q6/q3/q5/q67) plus coverage of every vectorized host subsystem —
# semi/anti joins (q22), string predicates (q14), conditional aggs
# (q12, xbb_q5), windows over computed aggregates (ds_q89, ds_q98).
HOST_SWEEP = (
    ("q1", tpch), ("q6", tpch), ("q3", tpch), ("q5", tpch),
    ("q12", tpch), ("q14", tpch), ("q22", tpch),
    ("q67", suites), ("xbb_q5", suites),
    ("ds_q89", suites), ("ds_q98", suites),
)


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("he_tpch")
    tpch.generate(str(d), scale=0.01, files_per_table=2)
    return str(d)


@pytest.fixture(scope="module")
def suites_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("he_suites")
    suites.generate(str(d), scale=0.02, files_per_table=2)
    return str(d)


def _session():
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.hasNans", False)
    return s


def _run_sweep(qn, mod, data_dir):
    df = mod.QUERIES[qn](_session(), data_dir)
    want_dev = df.collect()
    got_host = df.collect_host()
    # Queries ordered by a computed float (mod._SET_COMPARE) tie-break
    # arbitrarily between engines; compare those as row sets, like the
    # pandas oracle does.
    srt = qn in mod._SET_COMPARE
    assert compare_results(got_host, want_dev, sort=srt), (
        f"{qn}: host engine diverged from device: "
        f"{first_mismatch(got_host, want_dev, sort=srt)}")
    want_pd = mod.pandas_query(qn, data_dir)
    assert mod.check_result(qn, got_host, want_pd), (
        f"{qn}: host engine diverged from the pandas oracle")


@pytest.mark.parametrize("qn", ["q1", "q6"])
def test_host_parity_fast(qn, tpch_dir):
    _run_sweep(qn, tpch, tpch_dir)


@pytest.mark.slow
@pytest.mark.parametrize("qn,mod", [
    (qn, mod) for qn, mod in HOST_SWEEP if qn not in ("q1", "q6")])
def test_host_parity_sweep(qn, mod, tpch_dir, suites_dir):
    _run_sweep(qn, mod, tpch_dir if mod is tpch else suites_dir)


# ---------------------------------------------------------------------------
# all_valid helper contract
# ---------------------------------------------------------------------------

class TestAllValid:
    def test_shared_and_readonly(self):
        a = all_valid(10)
        b = all_valid(4)
        assert a.all() and b.all()
        assert len(a) == 10 and len(b) == 4
        # Same backing buffer, no per-call allocation.
        assert a.base is not None and a.base is b.base
        with pytest.raises(ValueError):
            a[0] = False

    def test_grows(self):
        n = len(all_valid(1).base) * 2 + 3
        big = all_valid(n)
        assert len(big) == n and big.all()


# ---------------------------------------------------------------------------
# Null propagation per expression family (host engine)
# ---------------------------------------------------------------------------

def _host_nulls(expr, batch):
    """Evaluate on the host engine, return the per-row null mask."""
    col = expr.eval_host(batch)
    from spark_rapids_tpu.exprs.base import as_host_column
    col = as_host_column(col, batch)
    return [not v for v in np.asarray(col.validity, np.bool_)]


NUM_BATCH = HostBatch.from_pydict(
    [("a", dt.INT64), ("b", dt.INT64)],
    {"a": [1, None, 3, None], "b": [10, 20, None, None]})

STR_BATCH = HostBatch.from_pydict(
    [("s", dt.STRING), ("t", dt.STRING)],
    {"s": ["ab", None, "cd", None], "t": ["x", "y", None, None]})


class TestNullPropagation:
    def test_arithmetic(self):
        expr = E.Add(Ref(0, dt.INT64), Ref(1, dt.INT64))
        assert _host_nulls(expr, NUM_BATCH) == [False, True, True, True]

    def test_predicates(self):
        expr = E.LessThan(Ref(0, dt.INT64), Ref(1, dt.INT64))
        assert _host_nulls(expr, NUM_BATCH) == [False, True, True, True]
        # IsNull itself never yields null.
        assert _host_nulls(E.IsNull(Ref(0, dt.INT64)), NUM_BATCH) == \
            [False, False, False, False]

    def test_conditional(self):
        expr = E.If(E.IsNull(Ref(0, dt.INT64)), Ref(1, dt.INT64),
                    Ref(0, dt.INT64))
        # row0: a=1 -> a; row1: null -> b=20; row2: a=3; row3: b null.
        assert _host_nulls(expr, NUM_BATCH) == [False, False, False, True]
        expr = E.Coalesce(Ref(0, dt.INT64), Ref(1, dt.INT64))
        assert _host_nulls(expr, NUM_BATCH) == [False, False, False, True]

    def test_strings(self):
        expr = E.ConcatStrings(Ref(0, dt.STRING), Ref(1, dt.STRING))
        assert _host_nulls(expr, STR_BATCH) == [False, True, True, True]
        expr = E.Length(Ref(0, dt.STRING))
        assert _host_nulls(expr, STR_BATCH) == [False, True, False, True]

    def test_cast(self):
        expr = E.Cast(Ref(0, dt.INT64), dt.STRING)
        assert _host_nulls(expr, NUM_BATCH) == [False, True, False, True]
        # Parse failure nulls, input null propagates.
        bad = HostBatch.from_pydict(
            [("s", dt.STRING)], {"s": ["12", "xy", None, "7"]})
        expr = E.Cast(Ref(0, dt.STRING), dt.INT32)
        assert _host_nulls(expr, bad) == [False, True, True, False]

    def test_hash(self):
        # Hash of a null input is the seed — defined, never null.
        expr = E.Murmur3Hash([Ref(0, dt.INT64)])
        assert _host_nulls(expr, NUM_BATCH) == [False] * 4
