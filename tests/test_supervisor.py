"""Self-healing supervision tests (ISSUE 20).

Three layers, cheapest first:

- pure policy arithmetic (backoff schedule, crash-loop window,
  straggler outlier detection, drain ordering) with no processes;
- the :class:`Supervisor` state machine against FAKE worker processes
  (an injected spawn_fn returning scriptable handles), so restart /
  quarantine / drain transitions are deterministic and instant;
- coordinator verb-level drain semantics (CDRAIN vs in-flight stages,
  CDEMO placement demotion) via ``co.dispatch`` — no sockets;
- one real-process regression: ``--max-idle-s`` self-retirement now
  deregisters through the CDRAIN→CRETIRE handshake instead of
  silently exiting and waiting out the heartbeat sweep.
"""

import base64
import os
import subprocess
import sys
import time

import pytest

import spark_rapids_tpu
from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.memory.oom import is_transient_error
from spark_rapids_tpu.parallel import cluster as CL
from spark_rapids_tpu.parallel.cluster import coordinator as CO
from spark_rapids_tpu.parallel.cluster.supervisor import (
    BACKOFF, DRAINING, QUARANTINED, RETIRED, RUNNING, Supervisor,
    drain_order, is_crash_looping, restart_backoff_ms,
    straggler_verdicts)

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(spark_rapids_tpu.__file__)))


@pytest.fixture(autouse=True)
def clean_cluster_state():
    faults.configure("")
    faults.reset_counters()
    yield
    CL.shutdown_coordinator()
    faults.configure("")
    faults.reset_counters()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_supervisor"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


def _cluster_session(**over) -> TpuSession:
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.cluster.enabled", True)
    for k, v in over.items():
        s.set(k, v)
    return s


def _submit_q3(data_dir, **over):
    s = _cluster_session(**over)
    s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
    phys = tpch.QUERIES["q3"](s, data_dir)._physical()
    co = CL.get_coordinator(s.conf)
    q = co.submit(phys, s.conf)
    assert q is not None
    return co, q


# ---------------------------------------------------------------------------
# Policy units (pure, no processes)
# ---------------------------------------------------------------------------

class TestBackoffSchedule:
    def test_deterministic_exponential_with_cap(self):
        sched = [restart_backoff_ms(n, 250, 10000) for n in range(1, 9)]
        assert sched == [250.0, 500.0, 1000.0, 2000.0, 4000.0,
                         8000.0, 10000.0, 10000.0]
        # Determinism: same inputs, same schedule, no jitter.
        assert sched == [restart_backoff_ms(n, 250, 10000)
                         for n in range(1, 9)]

    def test_zero_deaths_no_wait_and_huge_counts_stay_capped(self):
        assert restart_backoff_ms(0, 250, 10000) == 0.0
        # 2**(n-1) overflow guard: the cap holds at absurd counts.
        assert restart_backoff_ms(10_000, 250, 10000) == 10000.0


class TestCrashLoopWindow:
    def test_threshold_inside_window_quarantines(self):
        # 3 deaths within 30s of "now" -> looping.
        assert is_crash_looping([70.0, 80.0, 90.0], 100.0, 30000, 3)

    def test_old_deaths_age_out(self):
        # Only 2 of 3 deaths inside the trailing window: not looping.
        assert not is_crash_looping([60.0, 80.0, 90.0], 100.0,
                                    30000, 3)
        # The SAME history judged earlier (window ends sooner) loops:
        # the window is trailing from ``now``, not absolute.
        assert is_crash_looping([60.0, 80.0, 90.0], 90.0, 30000, 3)

    def test_exact_boundary_counts(self):
        # A death exactly window_ms ago is still inside (>= cutoff).
        assert is_crash_looping([70.0, 85.0, 100.0], 100.0, 30000, 3)


class TestStragglerDetection:
    def test_outlier_demoted_healthy_not(self):
        v = straggler_verdicts(
            {"a": [10.0] * 6, "b": [12.0] * 6, "c": [95.0] * 6},
            factor=3.0, min_samples=5)
        assert v == {"a": False, "b": False, "c": True}

    def test_min_samples_gate(self):
        # c is 10x slower but has too few samples to judge; a fleet of
        # one judgeable worker can't have outliers either.
        v = straggler_verdicts(
            {"a": [10.0] * 6, "c": [100.0] * 2},
            factor=3.0, min_samples=5)
        assert v == {"a": False, "c": False}

    def test_promote_back_hysteresis(self):
        # A demoted worker at 2.5x fleet median stays demoted (above
        # factor/2 = 1.5x) — no flapping at the threshold...
        v = straggler_verdicts(
            {"a": [10.0] * 6, "b": [10.0] * 6, "c": [25.0] * 6},
            factor=3.0, min_samples=5, demoted={"c"})
        assert v["c"] is True
        # ...and only promotes once clearly recovered (under 1.5x).
        v = straggler_verdicts(
            {"a": [10.0] * 6, "b": [10.0] * 6, "c": [12.0] * 6},
            factor=3.0, min_samples=5, demoted={"c"})
        assert v["c"] is False

    def test_synthetic_trace_with_noise(self):
        # Realistic shape: jittery healthy workers, one 5x straggler.
        healthy = [48.0, 52.0, 50.0, 47.0, 55.0, 51.0, 49.0]
        slow = [x * 5 for x in healthy]
        v = straggler_verdicts(
            {"w0": healthy, "w1": list(reversed(healthy)),
             "w2": healthy[1:] + healthy[:1], "w3": slow},
            factor=3.0, min_samples=5)
        assert v == {"w0": False, "w1": False, "w2": False,
                     "w3": True}


class TestDrainOrder:
    def test_demoted_then_least_useful(self):
        order = drain_order({
            "a": {"demoted": False, "completed": 9, "idle_ms": 0},
            "b": {"demoted": True, "completed": 50, "idle_ms": 0},
            "c": {"demoted": False, "completed": 2, "idle_ms": 500},
        })
        assert order == ["b", "c", "a"]

    def test_idle_breaks_ties(self):
        order = drain_order({
            "a": {"demoted": False, "completed": 5, "idle_ms": 10},
            "b": {"demoted": False, "completed": 5, "idle_ms": 900},
        })
        assert order == ["b", "a"]


# ---------------------------------------------------------------------------
# Supervisor state machine against fake processes
# ---------------------------------------------------------------------------

class FakeProc:
    """Scriptable stand-in for subprocess.Popen: tests flip ``rc``."""

    def __init__(self):
        self.rc = None
        self.terminated = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True
        if self.rc is None:
            self.rc = -15

    def wait(self, timeout=None):
        return self.rc

    def kill(self):
        self.rc = -9


def _conf(**over):
    s = TpuSession()
    for k, v in over.items():
        s.set(k, v)
    return s.conf


def _fake_supervisor(verbs=None, stats=None, **conf_over):
    spawned = []

    def spawn(wid, env):
        p = FakeProc()
        spawned.append((wid, dict(env)))
        return p

    if verbs is None:
        def verb_fn(line):
            return "OK"
    else:
        def verb_fn(line):
            verbs.append(line)
            return "OK"
    sup = Supervisor(
        "127.0.0.1:1", conf=_conf(**conf_over), prefix="t",
        spawn_fn=spawn,
        stats_fn=(lambda: stats) if stats is not None
        else (lambda: {"workers": {}}),
        verb_fn=verb_fn)
    return sup, spawned


class TestSupervisorRestarts:
    def test_death_restarts_after_backoff_same_wid_same_env(self):
        sup, spawned = _fake_supervisor(**{
            "spark.rapids.sql.cluster.supervisor.restartBackoffBaseMs": 200})
        wid = sup.add_worker(extra_env={"MARKER": "x"})
        now = 100.0
        sup.workers[wid].proc.rc = 1          # dies
        sup.tick(now)
        mw = sup.workers[wid]
        assert mw.state == BACKOFF and mw.deaths == 1
        assert mw.next_restart_at == pytest.approx(now + 0.2)
        sup.tick(now + 0.1)                   # still inside backoff
        assert mw.state == BACKOFF
        sup.tick(now + 0.25)                  # past it: respawned
        assert mw.state == RUNNING and mw.restarts == 1
        assert sup.counters["restarts"] == 1
        # restarted under the SAME wid, with the seeded env preserved
        assert [w for w, _ in spawned] == [wid, wid]
        assert spawned[1][1]["MARKER"] == "x"

    def test_second_death_doubles_backoff(self):
        sup, _ = _fake_supervisor(**{
            "spark.rapids.sql.cluster.supervisor.restartBackoffBaseMs": 200,
            "spark.rapids.sql.cluster.supervisor.crashLoopWindowMs":
                1000})
        wid = sup.add_worker()
        mw = sup.workers[wid]
        mw.proc.rc = 1
        sup.tick(10.0)
        sup.tick(10.3)
        mw.proc.rc = 1                        # dies again at 20s —
        sup.tick(20.0)                        # outside the loop window
        assert mw.state == BACKOFF
        assert mw.next_restart_at == pytest.approx(20.0 + 0.4)

    def test_clean_exit_is_retirement_not_death(self):
        sup, _ = _fake_supervisor()
        wid = sup.add_worker()
        sup.workers[wid].proc.rc = 0
        sup.tick(1.0)
        mw = sup.workers[wid]
        assert mw.state == RETIRED and mw.deaths == 0
        assert sup.counters["retirements"] == 1
        sup.tick(2.0)                         # and stays retired
        assert mw.state == RETIRED


class TestSupervisorQuarantine:
    def test_crash_loop_quarantines_and_never_respawns(self):
        sup, spawned = _fake_supervisor(**{
            "spark.rapids.sql.cluster.supervisor.restartBackoffBaseMs": 1,
            "spark.rapids.sql.cluster.supervisor.crashLoopWindowMs":
                30000,
            "spark.rapids.sql.cluster.supervisor.crashLoopThreshold":
                3})
        wid = sup.add_worker(extra_env={"SRT_FAULTS": "boom"})
        mw = sup.workers[wid]
        now = 50.0
        for _ in range(2):                    # deaths 1 and 2: backoff
            mw.proc.rc = 1
            sup.tick(now)
            assert mw.state == BACKOFF
            now += 1.0
            sup.tick(now)                     # respawn
            assert mw.state == RUNNING
            now += 1.0
        mw.proc.rc = 1                        # death 3 inside window
        sup.tick(now)
        assert mw.state == QUARANTINED
        assert "crash-loop" in mw.reason
        assert sup.counters["quarantines"] == 1
        assert wid in sup.quarantined()
        n_spawns = len(spawned)
        sup.tick(now + 100.0)                 # held out forever
        assert mw.state == QUARANTINED and len(spawned) == n_spawns
        assert sup.active_count() == 0

    def test_slow_deaths_outside_window_keep_restarting(self):
        sup, spawned = _fake_supervisor(**{
            "spark.rapids.sql.cluster.supervisor.restartBackoffBaseMs": 1,
            "spark.rapids.sql.cluster.supervisor.crashLoopWindowMs":
                10000,
            "spark.rapids.sql.cluster.supervisor.crashLoopThreshold":
                3})
        wid = sup.add_worker()
        mw = sup.workers[wid]
        now = 0.0
        for _ in range(5):                    # one death per minute
            mw.proc.rc = 1
            sup.tick(now)
            assert mw.state == BACKOFF
            sup.tick(now + 11.0)
            assert mw.state == RUNNING
            now += 60.0
        assert mw.deaths == 5 and mw.state == RUNNING
        assert len(spawned) == 6              # initial + 5 restarts


class TestSupervisorDrain:
    def test_drain_sends_cdrain_and_reaps_clean_exit(self):
        verbs = []
        sup, _ = _fake_supervisor(verbs=verbs)
        wid = sup.add_worker()
        assert sup.drain(wid)
        assert f"CDRAIN {wid}" in verbs
        mw = sup.workers[wid]
        assert mw.state == DRAINING
        assert sup.active_count() == 0        # leaving: not counted
        mw.proc.rc = 0                        # worker got CRETIRE
        sup.tick(1.0)
        assert mw.state == RETIRED
        assert sup.counters["drains"] == 1
        assert not sup.drain(wid)             # idempotent-ish: no-op

    def test_drain_timeout_escalates_to_terminate(self):
        sup, _ = _fake_supervisor(**{
            "spark.rapids.sql.cluster.supervisor.drainTimeoutMs": 100})
        wid = sup.add_worker()
        t0 = time.monotonic()
        sup.drain(wid)
        mw = sup.workers[wid]
        sup.tick(t0 + 0.05)                   # inside the window
        assert not mw.proc.terminated
        sup.tick(t0 + 0.5)                    # past it
        assert mw.proc.terminated
        sup.tick(t0 + 0.6)
        assert mw.state == RETIRED            # reaped after terminate

    def test_scale_to_prefers_draining_demoted(self):
        stats = {"workers": {
            "t0": {"alive": True, "demoted": False, "completed": 9,
                   "idle_ms": 0},
            "t1": {"alive": True, "demoted": True, "completed": 9,
                   "idle_ms": 0},
            "t2": {"alive": True, "demoted": False, "completed": 1,
                   "idle_ms": 0},
        }}
        sup, _ = _fake_supervisor(stats=stats)
        for _ in range(3):
            sup.add_worker()
        assert sup.scale_to(2) == -1
        assert sup.workers["t1"].state == DRAINING   # the straggler
        assert {w.wid for w in sup.workers.values()
                if w.state == RUNNING} == {"t0", "t2"}

    def test_scale_to_skips_recently_dead_workers(self):
        """Capacity scale-down never drains a worker with a death
        inside the crash-loop window — draining a flapper would
        launder a crash-looper into a clean retirement before it can
        burn its restart budget into quarantine."""
        stats = {"workers": {
            "t0": {"alive": True, "demoted": False, "completed": 9,
                   "idle_ms": 0},
            "t1": {"alive": True, "demoted": False, "completed": 0,
                   "idle_ms": 500},
        }}
        sup, _ = _fake_supervisor(stats=stats)
        for _ in range(2):
            sup.add_worker()
        # t1 ranks first in drain_order (fewest completed, most idle)
        # but just died once: scale-down must pick t0 instead.
        sup.workers["t1"].death_ts.append(time.monotonic())
        assert sup.scale_to(1) == -1
        assert sup.workers["t0"].state == DRAINING
        assert sup.workers["t1"].state == RUNNING

    def test_scale_to_spawns_up(self):
        sup, spawned = _fake_supervisor()
        sup.add_worker()
        assert sup.scale_to(3) == 2
        assert sup.active_count() == 3 and len(spawned) == 3


class TestSupervisorStragglerScan:
    def test_demotes_then_promotes_via_cdemo(self):
        stats = {"workers": {
            "t0": {"alive": True, "beat_ms": [10.0] * 6,
                   "stage_wall_ms": [100.0] * 6},
            "t1": {"alive": True, "beat_ms": [11.0] * 6,
                   "stage_wall_ms": [110.0] * 6},
            "t2": {"alive": True, "beat_ms": [12.0] * 6,
                   "stage_wall_ms": [900.0] * 6},
        }}
        verbs = []
        sup, _ = _fake_supervisor(verbs=verbs, stats=stats)
        for _ in range(3):
            sup.add_worker()
        sup.tick(1.0)
        assert "CDEMO t2 1" in verbs
        assert sup.counters["demotions"] == 1
        sup.tick(2.0)                         # still slow: no re-send
        assert verbs.count("CDEMO t2 1") == 1
        stats["workers"]["t2"]["stage_wall_ms"] = [115.0] * 6
        sup.tick(3.0)                         # recovered on BOTH axes
        assert "CDEMO t2 0" in verbs
        assert sup.counters["promotions"] == 1


# ---------------------------------------------------------------------------
# Coordinator verb-level drain semantics (no worker processes)
# ---------------------------------------------------------------------------

class TestDrainVerbOrdering:
    def test_drain_waits_for_inflight_stage_then_retires(self, data_dir):
        """CDRAIN ordering: stop dispatching immediately, let the
        in-flight stage COMMIT, only then answer CRETIRE — scale-down
        never costs a recompute."""
        co, q = _submit_q3(data_dir)
        co.dispatch(["CREG", "wA"])
        resp = co.dispatch(["CPOLL", "wA", "-"]).decode().split()
        assert resp[0] == "CTASK"
        qid, sid, gen = int(resp[1]), int(resp[2]), int(resp[3])
        assert co.dispatch(["CDRAIN", "wA"]) == b"OK\n"
        # In-flight stage not yet committed: poll must NOT retire the
        # worker (that would orphan the stage) and must NOT hand out
        # new work either.
        assert co.dispatch(["CPOLL", "wA", "-"]) == b"CIDLE -\n"
        assert q.tasks[sid].status == "running"
        assert co.dispatch(
            ["CDONE", "wA", str(qid), str(sid), str(gen),
             "50"]) == b"OK\n"
        # Committed: the next poll retires.
        assert co.dispatch(["CPOLL", "wA", "-"]) == b"CRETIRE\n"
        assert "wA" not in co.stats()["workers"]
        assert "wA" in co.stats()["retired"]
        assert q.tasks[sid].status == "done"          # no recompute
        assert faults.counters().get("clusterWorkerDeaths", 0) == 0
        assert faults.counters().get(
            "clusterWorkerRetirements", 0) == 1

    def test_drained_work_reroutes_to_peers(self, data_dir):
        co, q = _submit_q3(data_dir)
        co.dispatch(["CREG", "wA"])
        co.dispatch(["CREG", "wB"])
        co.dispatch(["CDRAIN", "wA"])
        # wA holds nothing: retires on its next poll; the whole query
        # drains through wB.
        assert co.dispatch(["CPOLL", "wA", "-"]) == b"CRETIRE\n"
        while True:
            resp = co.dispatch(["CPOLL", "wB", "-"]).decode().split()
            if resp[0] == "CIDLE":
                break
            co.dispatch(["CDONE", "wB", resp[1], resp[2], resp[3],
                         "10"])
        assert all(t.status == "done" and t.producer == "wB"
                   for t in q.tasks.values())

    def test_cretire_idempotent_and_stale_beat_swallowed(self, data_dir):
        co, _ = _submit_q3(data_dir)
        co.dispatch(["CREG", "wA"])
        co.dispatch(["CDRAIN", "wA"])
        assert co.dispatch(["CPOLL", "wA", "-"]) == b"CRETIRE\n"
        # The worker's daemon heartbeat may land once more, and a
        # duplicate poll may race the exit: neither resurrects it.
        assert co.dispatch(["CBEAT", "wA"]) == b"OK\n"
        assert co.dispatch(["CPOLL", "wA", "-"]) == b"CRETIRE\n"
        assert "wA" not in co.stats()["workers"]

    def test_fast_restart_requeues_orphaned_stage(self, data_dir):
        """Incarnation tokens: a supervisor restart re-registers the
        SAME wid, and on a loaded host that CREG can land BEFORE the
        heartbeat sweep notices the old process went silent. The new
        token is proof of death — the dead incarnation's RUNNING stage
        requeues immediately instead of staying assigned to a wid that
        keeps beating (a permanent dispatch stall)."""
        co, q = _submit_q3(data_dir)
        co.dispatch(["CREG", "wA", "pid1"])
        resp = co.dispatch(["CPOLL", "wA", "-"]).decode().split()
        assert resp[0] == "CTASK"
        sid, gen = int(resp[2]), int(resp[3])
        assert q.tasks[sid].status == "running"
        # SIGKILL + instant respawn under the same wid, new process.
        assert co.dispatch(["CREG", "wA", "pid2"]) == b"OK\n"
        t = q.tasks[sid]
        assert t.status == "pending"
        assert t.gen == gen + 1
        assert faults.counters().get("clusterWorkerDeaths", 0) == 1
        # The replacement immediately wins work again.
        resp = co.dispatch(["CPOLL", "wA", "-"]).decode().split()
        assert resp[0] == "CTASK"

    def test_same_token_reconnect_keeps_inflight_stage(self, data_dir):
        """A live worker re-registering after a coordinator hiccup
        (same process, same token) is NOT a death — its in-flight
        stage keeps running and no requeue happens."""
        co, q = _submit_q3(data_dir)
        co.dispatch(["CREG", "wA", "pid1"])
        resp = co.dispatch(["CPOLL", "wA", "-"]).decode().split()
        sid = int(resp[2])
        assert co.dispatch(["CREG", "wA", "pid1"]) == b"OK\n"
        assert q.tasks[sid].status == "running"
        # Tokenless CREG (legacy form) is a plain touch too.
        assert co.dispatch(["CREG", "wA"]) == b"OK\n"
        assert q.tasks[sid].status == "running"
        assert faults.counters().get("clusterWorkerDeaths", 0) == 0

    def test_cdemo_deprioritizes_placement(self, data_dir):
        """A demoted worker ranks below every undemoted peer in
        _pick_locked — it only receives work when it is the sole
        eligible candidate."""
        co, q = _submit_q3(data_dir, **{
            "spark.rapids.sql.cluster.stealDelayMs": 60000})
        co.dispatch(["CREG", "wFast"])
        co.dispatch(["CREG", "wSlow"])
        assert co.dispatch(["CDEMO", "wSlow", "1"]) == b"OK\n"
        assert co.stats()["workers"]["wSlow"]["demoted"] is True
        # With the fast worker mid-steal-delay-free (both idle), the
        # demoted one polls first yet gets nothing while wFast exists
        # and work remains unreserved for it... the cheap invariant to
        # pin without timing games: wFast drains the DAG solo even
        # though wSlow polls eagerly, because every pick prefers it.
        done = 0
        for _ in range(200):
            r = co.dispatch(["CPOLL", "wSlow", "-"]).decode().split()
            if r[0] == "CTASK":
                # demoted may still serve as fallback-of-last-resort
                # for tasks wFast can't take (none here: requeue path)
                co.dispatch(["CDONE", "wSlow", r[1], r[2], r[3], "5"])
            r = co.dispatch(["CPOLL", "wFast", "-"]).decode().split()
            if r[0] == "CTASK":
                co.dispatch(["CDONE", "wFast", r[1], r[2], r[3], "5"])
                done += 1
            if all(t.status == "done" for t in q.tasks.values()):
                break
        assert all(t.status == "done" for t in q.tasks.values())
        producers = {t.producer for t in q.tasks.values()}
        assert producers == {"wFast"}
        assert co.dispatch(["CDEMO", "wSlow", "0"]) == b"OK\n"
        assert co.stats()["workers"]["wSlow"]["demoted"] is False


# ---------------------------------------------------------------------------
# Dispatch-timeout rejection carries the retry contract (satellite)
# ---------------------------------------------------------------------------

class TestDispatchTimeoutHint:
    def test_barrier_timeout_is_typed_hinted_and_transient(self, data_dir):
        from spark_rapids_tpu.parallel.scheduler import (
            QueryRejectedError)
        co, q = _submit_q3(data_dir, **{
            "spark.rapids.sql.cluster.dispatchTimeoutMs": 120})
        co.dispatch(["CREG", "wA"])           # min-workers gate opens
        with pytest.raises(QueryRejectedError) as ei:
            q.run(None)                       # nobody ever polls
        e = ei.value
        assert isinstance(e, CO.ClusterDispatchError)
        assert e.kind == "dispatch-timeout"
        assert e.retry_after_ms is not None and e.retry_after_ms > 0
        assert e.queue_depth == len(q.tasks)
        assert "UNAVAILABLE" in str(e)
        assert is_transient_error(e)          # recovery-ladder eligible


# ---------------------------------------------------------------------------
# Real-process regression: --max-idle-s self-retirement deregisters
# ---------------------------------------------------------------------------

def _spawn_worker(addr, wid, extra_args=(), extra_env=None):
    cmd = [sys.executable, "-m",
           "spark_rapids_tpu.parallel.cluster.worker",
           "--coordinator", addr, "--worker-id", wid,
           "--heartbeat-ms", "200"] + list(extra_args)
    env = dict(os.environ)
    env.pop("SRT_FAULTS", None)
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(cmd, env=env, cwd=REPO_ROOT)


class TestMaxIdleSelfRetirement:
    @pytest.mark.slow  # real worker process; runs in the CI
    # `autoscaler` chaos entry (no `-m 'not slow'` filter there).
    def test_idle_worker_deregisters_instead_of_silent_exit(self, data_dir):
        """Pre-ISSUE-20, --max-idle-s expiry just exited: membership
        lingered until the heartbeat sweep timed out and counted a
        DEATH. Now the worker drains itself (CDRAIN → CRETIRE): clean
        exit 0, immediate membership drop, a retirement — zero deaths
        — even with the heartbeat timeout cranked to a minute."""
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        want = tpch.QUERIES["q3"](s, data_dir).collect()
        sc = _cluster_session(**{
            "spark.rapids.sql.cluster.heartbeatTimeoutMs": 60000})
        co = CL.get_coordinator(sc.conf)
        addr = f"{co.addr[0]}:{co.addr[1]}"
        p = _spawn_worker(addr, "solo", ["--max-idle-s", "1.0",
                                        "--poll-ms", "25"])
        try:
            # 1-task pool runs the whole query, then idles out.
            got = tpch.QUERIES["q3"](sc, data_dir).collect()
            assert got == want
            rc = p.wait(timeout=30)
            assert rc == 0
            st = co.stats()
            assert "solo" not in st["workers"]
            assert "solo" in st["retired"]
            cnt = faults.counters()
            assert cnt.get("clusterWorkerDeaths", 0) == 0
            assert cnt.get("clusterWorkerRetirements", 0) == 1
        finally:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
