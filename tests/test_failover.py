"""Coordinator failover (ISSUE 17): the write-ahead journal, its pure
replay fold, worker reconnect-instead-of-die, and the acceptance
scenario — a standalone coordinator SIGKILLed mid-query and restarted
in place, with the remote driver riding out the outage and the query
finishing bit-identical at ≤1 stage recompute and zero whole-query
retries.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

import spark_rapids_tpu
from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.parallel import cluster as CL
from spark_rapids_tpu.parallel.cluster.journal import (Journal,
                                                       replay_state)
from spark_rapids_tpu.parallel.transport import rendezvous as RV

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(spark_rapids_tpu.__file__)))


@pytest.fixture(autouse=True)
def _clean():
    faults.configure("")
    faults.reset_counters()
    yield
    CL.shutdown_coordinator()
    faults.configure("")
    faults.reset_counters()


# ---------------------------------------------------------------------------
# Journal: append / read / torn tail / compaction
# ---------------------------------------------------------------------------

def test_journal_roundtrip_and_torn_tail(tmp_path):
    j = Journal(str(tmp_path / "journal" / "j.jsonl"))
    j.append({"t": "reg", "wid": "w0"})
    j.append({"t": "submit", "qid": 1, "stages": [1, 2], "deps": {}})
    recs = j.records()
    assert [r["t"] for r in recs] == ["reg", "submit"]
    assert all("ts" in r for r in recs)        # stamped automatically
    # A crash mid-append leaves a torn trailing line: skipped, earlier
    # records intact — never a parse error.
    with open(j.path, "a", encoding="utf-8") as f:
        f.write('{"t": "dispatch", "qid": 1, "si')
    assert [r["t"] for r in j.records()] == ["reg", "submit"]


def test_journal_append_never_raises(tmp_path):
    j = Journal(str(tmp_path / "j.jsonl"))
    j.append({"t": "bad", "blob": object()})   # unserializable: warned
    assert j.records() == []                   # not torn, just absent


def test_journal_compaction_is_atomic_rewrite(tmp_path):
    j = Journal(str(tmp_path / "j.jsonl"))
    for i in range(5):
        j.append({"t": "reg", "wid": f"w{i}"})
    j.append({"t": "submit", "qid": 1, "stages": [1], "deps": {}})
    j.append({"t": "finish", "qid": 1})
    j.rewrite([{"t": "reg", "wid": "w0"}])
    assert [r["wid"] for r in j.records()] == ["w0"]
    assert not os.path.exists(j.path + ".tmp")


# ---------------------------------------------------------------------------
# replay_state: the pure recovery fold
# ---------------------------------------------------------------------------

def _submit(qid, stages):
    return {"t": "submit", "qid": qid, "stages": stages,
            "deps": {str(s): [] for s in stages}}


def test_replay_state_rebuilds_tasks_and_workers():
    st = replay_state([
        {"t": "reg", "wid": "w0"}, {"t": "reg", "wid": "w1"},
        {"t": "reg", "wid": "w0"},            # re-register: no dup
        _submit(1, [1, 2, 3]),
        {"t": "dispatch", "qid": 1, "sid": 1, "gen": 0, "wid": "w0"},
        {"t": "done", "qid": 1, "sid": 1, "gen": 0, "wid": "w0",
         "bytes": 512},
        {"t": "dispatch", "qid": 1, "sid": 2, "gen": 0, "wid": "w1"},
    ])
    assert st["workers"] == ["w0", "w1"]
    assert st["next_qid"] == 2
    tasks = st["queries"][1]["tasks"]
    assert tasks[1] == {"status": "done", "gen": 0, "wid": "w0",
                        "bytes": 512, "retries": 0}
    assert tasks[2]["status"] == "running" and tasks[2]["wid"] == "w1"
    assert tasks[3]["status"] == "pending"


def test_replay_state_finished_queries_dropped_stale_gens_ignored():
    st = replay_state([
        _submit(1, [1]), _submit(2, [1]),
        {"t": "dispatch", "qid": 1, "sid": 1, "gen": 0, "wid": "w0"},
        {"t": "requeue", "qid": 1, "sid": 1, "gen": 1, "retries": 1},
        # the zombie's stale-generation records arrive late: ignored
        {"t": "done", "qid": 1, "sid": 1, "gen": 0, "wid": "w0",
         "bytes": 9},
        {"t": "finish", "qid": 2},
    ])
    assert list(st["queries"]) == [1]
    t = st["queries"][1]["tasks"][1]
    assert t["status"] == "pending" and t["gen"] == 1 \
        and t["retries"] == 1
    assert st["next_qid"] == 3                 # qids never reused


def test_replay_state_recompute_baseline_counting():
    st = replay_state([
        _submit(1, [1, 2]),
        {"t": "requeue", "qid": 1, "sid": 1, "gen": 1, "retries": 1},
        {"t": "requeue", "qid": 1, "sid": 2, "gen": 1, "retries": 1,
         "counted": False},                    # e.g. replay's own requeue
    ])
    # A restarted coordinator must report pre-crash recomputes as the
    # BASELINE, not as fresh ones — the remote driver mirrors deltas.
    assert st["queries"][1]["recomputes"] == 1


def test_replay_state_reset_clears_all_tasks():
    st = replay_state([
        _submit(1, [1, 2]),
        {"t": "done", "qid": 1, "sid": 1, "gen": 0, "wid": "w0",
         "bytes": 4},
        {"t": "reset", "qid": 1},
    ])
    assert all(t["status"] == "pending" and t["bytes"] == 0
               for t in st["queries"][1]["tasks"].values())


# ---------------------------------------------------------------------------
# Standalone coordinator + worker reconnect
# ---------------------------------------------------------------------------

def _free_addr():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return f"127.0.0.1:{port}"


def _start_coordinator(addr, cdir, hb_ms=3000):
    env = dict(os.environ)
    env.pop("SRT_FAULTS", None)
    p = subprocess.Popen(
        [sys.executable, "-m",
         "spark_rapids_tpu.parallel.cluster.coordinator",
         "--listen", addr, "--dir", cdir,
         "--heartbeat-timeout-ms", str(hb_ms)],
        env=env, cwd=REPO_ROOT, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT)
    while True:     # runpy may emit a warning line first; scan for it
        line = p.stdout.readline().decode()
        assert line, "coordinator died before listening"
        if "listening" in line:
            return p


def _spawn_worker(addr, wid, extra=()):
    env = dict(os.environ)
    env.pop("SRT_FAULTS", None)
    return subprocess.Popen(
        [sys.executable, "-m",
         "spark_rapids_tpu.parallel.cluster.worker",
         "--coordinator", addr, "--worker-id", wid, *extra],
        env=env, cwd=REPO_ROOT)


def _stop(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=20)
        except Exception:
            p.kill()


def _wire_stats(addr):
    import base64
    host, port = addr.split(":")
    resp = RV._roundtrip((host, int(port)), "CSTATS\n", timeout_s=5.0)
    assert resp.startswith("OK ")
    return json.loads(base64.b64decode(resp.split()[1]).decode())


def test_worker_reconnects_to_restarted_coordinator(tmp_path):
    """The reconnect bugfix: a worker whose coordinator vanishes backs
    off and re-registers when it returns, instead of exiting."""
    addr = _free_addr()
    cdir = str(tmp_path / "cluster")
    co = _start_coordinator(addr, cdir)
    w = _spawn_worker(addr, "wR", ("--heartbeat-ms", "300"))
    procs = [w]
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if "wR" in _wire_stats(addr)["workers"]:
                break
            time.sleep(0.1)
        assert "wR" in _wire_stats(addr)["workers"]
        co.send_signal(signal.SIGKILL)
        co.wait()
        time.sleep(1.0)                        # worker now in backoff
        assert w.poll() is None                # did NOT die on refused
        co = _start_coordinator(addr, cdir)    # same port (SO_REUSEADDR)
        deadline = time.time() + 30
        ok = False
        while time.time() < deadline:
            st = _wire_stats(addr)["workers"]
            if st.get("wR", {}).get("alive"):
                ok = True
                break
            time.sleep(0.2)
        assert ok, "worker failed to re-register after restart"
        # replay happened on the restart (journal is on by default here)
        recs = Journal(os.path.join(
            cdir, "journal", "journal.jsonl")).records()
        assert any(r.get("t") == "replay" for r in recs)
    finally:
        _stop(procs + [co])


# ---------------------------------------------------------------------------
# Acceptance scenario 1: SIGKILL the coordinator mid-query, restart it
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_failover"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


@pytest.mark.slow      # CI runs this via the coordinator-kill entry
def test_coordinator_sigkill_restart_resumes_query(data_dir, tmp_path):
    """Driver + 3 workers against a standalone journaled coordinator.
    The coordinator is SIGKILLed after the query's first dispatch and
    restarted on the same port/dir: the journal replays, committed
    stage outputs are re-adopted from their manifests, workers
    re-register, and the driver's poll loop rides out the outage. The
    result must be bit-identical with ≤1 stage recompute and zero
    whole-query retries."""
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    want = tpch.QUERIES["q3"](s, data_dir).collect()

    addr = _free_addr()
    cdir = str(tmp_path / "cluster")
    co = _start_coordinator(addr, cdir, hb_ms=4000)
    workers = [_spawn_worker(addr, f"w{i}") for i in range(3)]

    sc = TpuSession()
    sc.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    sc.set("spark.rapids.sql.cluster.enabled", True)
    sc.set("spark.rapids.sql.cluster.coordinator", addr)
    sc.set("spark.rapids.sql.cluster.coordinator.remote", True)
    sc.set("spark.rapids.sql.cluster.dir", cdir)
    sc.set("spark.rapids.sql.cluster.minWorkers", 3)
    sc.set("spark.rapids.sql.cluster.dispatchTimeoutMs", 300000)

    jpath = os.path.join(cdir, "journal", "journal.jsonl")
    c0 = dict(faults.counters())
    result = {}

    def run():
        result["got"] = tpch.QUERIES["q3"](sc, data_dir).collect()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        # Kill only once real work is journaled as in flight.
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                txt = open(jpath, encoding="utf-8").read()
            except OSError:
                txt = ""
            if '"t": "dispatch"' in txt:
                break
            time.sleep(0.05)
        assert '"t": "dispatch"' in txt, "no dispatch before deadline"
        co.send_signal(signal.SIGKILL)
        co.wait()
        time.sleep(1.0)
        co = _start_coordinator(addr, cdir, hb_ms=4000)
        t.join(timeout=240)
        assert not t.is_alive(), "query never finished after failover"
        c1 = faults.counters()
        delta = lambda k: c1.get(k, 0) - c0.get(k, 0)
        assert result["got"] == want             # bit-identical
        assert delta("stageRecomputes") <= 1     # ≤1 per injected crash
        assert delta("retriesAttempted") == 0    # never a dead query
        # The pre-kill snapshot proves real remote work was journaled;
        # post-restart the replay record survives even compaction.
        assert '"t": "submit"' in txt
        recs = Journal(jpath).records()
        assert any(r.get("t") == "replay" for r in recs)
    finally:
        _stop(workers + [co])
