"""SLO-driven autoscaling tests + the self-healing soak (ISSUE 20).

Fast layers run under tier-1:

- :func:`decide` policy arithmetic (scale-up triggers, cooldown,
  scale-down idle clock, min/max bounds) — pure, no processes;
- :class:`Autoscaler` acting through a fake-process supervisor;
- brownout interplay: sustained pressure consults the scale probe and
  DEFERS load shedding while the fleet has headroom.

The slow-marked soak is the ISSUE 20 acceptance: 2000 mixed
parameterized queries (SRT_SOAK=1; 120 in CI) x 4 tenants against an
autoscaled pool, with a mid-soak SIGKILL storm of half the fleet and a
seeded crash-looper. Every result bit-identical, healed deaths cost at
most one stage recompute each, the crash-looper ends quarantined, and
the fleet event log shows the worker count tracking load up AND down.
"""

import os
import threading
import time

import pytest

from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.monitoring import history
from spark_rapids_tpu.parallel import cluster as CL
from spark_rapids_tpu.parallel import scheduler as SC
from spark_rapids_tpu.parallel.cluster.autoscaler import (
    HOLD, SCALE_DOWN, SCALE_UP, Autoscaler, ScalerState, decide)
from spark_rapids_tpu.parallel.cluster.supervisor import (
    QUARANTINED, RUNNING, Supervisor)


@pytest.fixture(autouse=True)
def clean_cluster_state():
    faults.configure("")
    faults.reset_counters()
    SC.reset_counters()
    SC.register_scale_probe(None)
    yield
    CL.shutdown_coordinator()
    SC.register_scale_probe(None)
    faults.configure("")
    faults.reset_counters()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_autoscale"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


def _conf(**over):
    s = TpuSession()
    for k, v in over.items():
        s.set(k, v)
    return s.conf


KNOBS = dict(min_workers=1, max_workers=4, target_queued_ms=500.0,
             scale_up_step=1, scale_down_idle_s=10.0,
             cooldown_ms=5000.0)


# ---------------------------------------------------------------------------
# decide(): pure policy
# ---------------------------------------------------------------------------

class TestDecidePolicy:
    def test_scale_up_on_queued_ms_over_target(self):
        d = decide(100.0, 2, {"queued_ms": 900.0}, ScalerState(),
                   **KNOBS)
        assert d["action"] == SCALE_UP and d["target"] == 3

    def test_scale_up_when_queue_backed_up_and_all_busy(self):
        # Queued work with every worker occupied is overload even when
        # the wait quantile hasn't caught up yet.
        d = decide(100.0, 2, {"queue_depth": 3, "busy": 2},
                   ScalerState(), **KNOBS)
        assert d["action"] == SCALE_UP and d["target"] == 3
        # ...but a backed-up queue with idle workers is a dispatch gap,
        # not missing capacity.
        d = decide(100.0, 2, {"queue_depth": 3, "busy": 1},
                   ScalerState(), **KNOBS)
        assert d["action"] == HOLD

    def test_scale_up_step_and_ceiling(self):
        st = ScalerState()
        d = decide(100.0, 2, {"queued_ms": 900.0}, st,
                   **{**KNOBS, "scale_up_step": 3})
        assert d["target"] == 4                  # capped at max
        d = decide(100.0, 4, {"queued_ms": 900.0}, ScalerState(),
                   **KNOBS)
        assert d["action"] == HOLD and d["reason"] == "at-max-workers"

    def test_cooldown_gates_consecutive_decisions(self):
        st = ScalerState()
        st.last_action_at = 99.0                 # acted 1s ago
        d = decide(100.0, 2, {"queued_ms": 900.0}, st, **KNOBS)
        assert d["action"] == HOLD and d["reason"] == "cooldown"
        d = decide(105.0, 2, {"queued_ms": 900.0}, st, **KNOBS)
        assert d["action"] == SCALE_UP           # cooldown expired

    def test_scale_down_needs_sustained_idle_one_at_a_time(self):
        st = ScalerState()
        quiet = {"queued_ms": 10.0}
        d = decide(100.0, 3, quiet, st, **KNOBS)
        assert d["action"] == HOLD               # idle clock starts
        d = decide(105.0, 3, quiet, st, **KNOBS)
        assert d["action"] == HOLD               # 5s < scaleDownIdleS
        d = decide(111.0, 3, quiet, st, **KNOBS)
        assert d["action"] == SCALE_DOWN and d["target"] == 2

    def test_overload_blip_resets_idle_clock_even_in_cooldown(self):
        st = ScalerState()
        st.under_target_since = 95.0
        st.last_action_at = 99.9                 # cooling down
        d = decide(100.0, 3, {"queued_ms": 900.0}, st, **KNOBS)
        assert d["action"] == HOLD and d["reason"] == "cooldown"
        assert st.under_target_since is None     # hysteresis held
        d = decide(120.0, 3, {"queued_ms": 10.0}, st, **KNOBS)
        assert d["action"] == HOLD               # clock restarted...
        d = decide(131.0, 3, {"queued_ms": 10.0}, st, **KNOBS)
        assert d["action"] == SCALE_DOWN         # ...and re-ran fully

    def test_floor_min_workers(self):
        st = ScalerState()
        st.under_target_since = 0.0
        d = decide(100.0, 1, {"queued_ms": 0.0}, st, **KNOBS)
        assert d["action"] == HOLD and d["reason"] == "at-min-workers"

    def test_pressure_score_alone_triggers_scale_up(self):
        d = decide(100.0, 2, {"pressure": 1.2}, ScalerState(),
                   **KNOBS)
        assert d["action"] == SCALE_UP


# ---------------------------------------------------------------------------
# Autoscaler acting through a fake-process supervisor
# ---------------------------------------------------------------------------

class FakeProc:
    def __init__(self):
        self.rc = None

    def poll(self):
        return self.rc

    def terminate(self):
        self.rc = -15

    def wait(self, timeout=None):
        return self.rc

    def kill(self):
        self.rc = -9


def _fake_pair(sig, **conf_over):
    conf = _conf(**conf_over)
    sup = Supervisor("127.0.0.1:1", conf=conf, prefix="t",
                     spawn_fn=lambda wid, env: FakeProc(),
                     stats_fn=lambda: {"workers": {}},
                     verb_fn=lambda line: "OK")
    scaler = Autoscaler(sup, conf=conf, signals_fn=lambda: sig)
    return sup, scaler


class TestAutoscalerLoop:
    def test_scales_up_then_down_through_supervisor(self):
        sig = {"queued_ms": 900.0, "queue_depth": 2, "busy": 1}
        sup, scaler = _fake_pair(sig, **{
            "spark.rapids.sql.cluster.autoscale.maxWorkers": 3,
            "spark.rapids.sql.cluster.autoscale.cooldownMs": 0,
            "spark.rapids.sql.cluster.autoscale.scaleDownIdleS": 1})
        sup.add_worker()
        d = scaler.tick(100.0)
        assert d["action"] == SCALE_UP
        assert sup.active_count() == 2
        assert scaler.decisions["up"] == 1
        sig.update(queued_ms=0.0, queue_depth=0, busy=0)
        scaler.tick(200.0)                       # idle clock starts
        d = scaler.tick(202.0)
        assert d["action"] == SCALE_DOWN
        # Scale-down DRAINS (never kills): the worker leaves the
        # active set immediately and retires on clean exit.
        assert sup.active_count() == 1
        assert sup.counters["drains"] == 1
        assert scaler.decisions["down"] == 1

    def test_below_min_replenished_despite_cooldown(self):
        sig = {"queued_ms": 0.0}
        sup, scaler = _fake_pair(sig, **{
            "spark.rapids.sql.cluster.autoscale.minWorkers": 2})
        scaler.state.last_action_at = 99.9       # mid-cooldown
        d = scaler.tick(100.0)
        assert d["reason"] == "below-min-workers"
        assert sup.active_count() == 2

    def test_scale_probe_defers_below_max_declines_at_max(self):
        sig = {"queued_ms": 0.0}
        sup, scaler = _fake_pair(sig, **{
            "spark.rapids.sql.cluster.autoscale.maxWorkers": 2})
        sup.add_worker()
        assert scaler.scale_probe(1.5) is True   # headroom: defer
        assert sup.active_count() == 2           # and actually grew
        assert scaler.scale_probe(1.5) is False  # at max: shed load


class TestGatherSignals:
    def test_sees_real_admission_queue_depth(self):
        """Regression: queued_count is a PROPERTY — calling it like a
        method raised TypeError inside gather_signals' guard and the
        autoscaler was blind to queue depth (no scale-up ever fired in
        the soak). The real signal path must see a blocked admit."""
        conf = _conf(**{
            "spark.rapids.sql.scheduler.maxConcurrentQueries": 1})
        mgr = SC.get_query_manager(conf)._current()
        sup = Supervisor("127.0.0.1:1", conf=_conf(), prefix="g",
                         spawn_fn=lambda wid, env: FakeProc(),
                         stats_fn=lambda: {"workers": {}},
                         verb_fn=lambda line: "OK")
        scaler = Autoscaler(sup, conf=_conf())   # real gather_signals
        t1 = mgr.admit(conf)
        blocked = threading.Thread(
            target=lambda: mgr.finish(mgr.admit(conf)), daemon=True)
        blocked.start()
        try:
            deadline = time.monotonic() + 5.0
            depth = 0
            while time.monotonic() < deadline:
                depth = scaler.gather_signals()["queue_depth"]
                if depth >= 1:
                    break
                time.sleep(0.01)
            assert depth >= 1
        finally:
            mgr.finish(t1)
            blocked.join(timeout=5.0)


class TestBrownoutInterplay:
    def test_sustained_pressure_defers_to_scaleup_then_engages(self):
        """Capacity before degradation: with a live autoscaler below
        maxWorkers, sustained pressure triggers scale-up and brownout
        HOLDS OFF; once the probe declines (fleet at ceiling) the
        brownout safety valve engages as before."""
        conf = _conf(**{
            "spark.rapids.sql.scheduler.pressure.enabled": True,
            "spark.rapids.sql.scheduler.pressure.brownout.enterScore":
                0.9,
            "spark.rapids.sql.scheduler.pressure.brownout.sustainMs":
                0})
        mgr = SC.QueryManager(max_concurrent=2, queue_depth=4)
        probed = []

        def probe(score):
            probed.append(score)
            return True

        SC.register_scale_probe(probe)
        mgr.note_pressure(0.95, conf)
        mgr.note_pressure(0.95, conf)
        assert not mgr.brownout_active
        assert len(probed) >= 1 and probed[0] == 0.95
        assert SC.counters().get("brownoutDeferrals", 0) >= 1

        SC.register_scale_probe(lambda score: False)   # fleet at max
        mgr.note_pressure(0.95, conf)
        assert mgr.brownout_active
        assert SC.counters().get("brownouts", 0) == 1

    def test_no_probe_means_unchanged_brownout_behavior(self):
        conf = _conf(**{
            "spark.rapids.sql.scheduler.pressure.enabled": True,
            "spark.rapids.sql.scheduler.pressure.brownout.enterScore":
                0.9,
            "spark.rapids.sql.scheduler.pressure.brownout.sustainMs":
                0})
        mgr = SC.QueryManager(max_concurrent=2, queue_depth=4)
        mgr.note_pressure(0.95, conf)
        mgr.note_pressure(0.95, conf)
        assert mgr.brownout_active               # pre-ISSUE-20 path
        assert SC.counters().get("brownoutDeferrals", 0) == 0


# ---------------------------------------------------------------------------
# The acceptance soak (slow; 120 queries in CI, SRT_SOAK=1 runs 2000)
# ---------------------------------------------------------------------------

SEGMENTS = ["BUILDING", "AUTOMOBILE", "MACHINERY", "HOUSEHOLD"]
N_SLOTS = 12


def _shape_q3(s, data_dir, i):
    """Parameterized q3: the two-join shipping-priority shape with the
    date cut and market segment varying by slot — every query is
    shuffle-forced (dispatchable stages) under
    autoBroadcastJoinThreshold=-1."""
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col
    slot = i % N_SLOTS
    cut = tpch.days("1995-03-15") + (slot % 3) * 30 - 30
    seg = SEGMENTS[slot % 4]
    cust = tpch._read(s, data_dir, "customer") \
        .filter(col("c_mktsegment") == lit_col(seg)) \
        .select("c_custkey")
    orders = tpch._read(s, data_dir, "orders") \
        .filter(col("o_orderdate") < lit_col(cut)) \
        .select("o_orderkey", "o_custkey", "o_orderdate",
                "o_shippriority")
    li = tpch._read(s, data_dir, "lineitem") \
        .filter(col("l_shipdate") > lit_col(cut)) \
        .select("l_orderkey", "l_extendedprice", "l_discount")
    co = orders.join_on(cust, ["o_custkey"], ["c_custkey"])
    j = li.join_on(co, ["l_orderkey"], ["o_orderkey"])
    return j.group_by("l_orderkey", "o_orderdate", "o_shippriority") \
        .agg(agg_sum(col("l_extendedprice")
                     * (1.0 - col("l_discount"))).alias("revenue")) \
        .order_by(col("revenue").desc(), col("o_orderdate").asc()) \
        .limit(10)


@pytest.mark.slow
def test_autoscale_soak_self_healing(data_dir, tmp_path):
    """ISSUE 20 acceptance: 2000 (CI: 120) mixed parameterized queries
    x 4 tenants against an autoscaled pool. Mid-soak a SIGKILL storm
    takes out half the fleet (healed: <= 1 stage recompute per death,
    bit-identical results) and a seeded crash-looper burns through its
    restart budget into quarantine. The fleet event log must show the
    worker count tracking load: scale-ups while the clients hammer,
    scale-downs once they stop."""
    total = 2000 if os.environ.get("SRT_SOAK", "").strip() \
        not in ("", "0") else 120
    fleet_dir = str(tmp_path / "fleet")

    def session():
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
        s.set("spark.rapids.sql.cluster.enabled", True)
        s.set("spark.rapids.sql.cluster.heartbeatTimeoutMs", 1500)
        s.set("spark.rapids.sql.eventLog.dir", fleet_dir)
        return s

    # Solo reference pass (local, no cluster) per parameter slot.
    ref = TpuSession()
    ref.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    ref.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
    expected = {slot: _shape_q3(ref, data_dir, slot).collect()
                for slot in range(N_SLOTS)}

    sessions = [session() for _ in range(4)]
    co = CL.get_coordinator(sessions[0].conf)
    addr = f"{co.addr[0]}:{co.addr[1]}"

    aconf = _conf(**{
        "spark.rapids.sql.cluster.autoscale.minWorkers": 1,
        "spark.rapids.sql.cluster.autoscale.maxWorkers": 3,
        "spark.rapids.sql.cluster.autoscale.targetQueuedMs": 50,
        "spark.rapids.sql.cluster.autoscale.scaleDownIdleS": 2,
        "spark.rapids.sql.cluster.autoscale.cooldownMs": 1000,
        "spark.rapids.sql.cluster.supervisor.pollMs": 100,
        "spark.rapids.sql.cluster.supervisor.restartBackoffBaseMs":
            100,
        "spark.rapids.sql.cluster.supervisor.crashLoopThreshold": 3,
    })
    sup = Supervisor(addr, conf=aconf, prefix="a", heartbeat_ms=500)
    scaler = Autoscaler(sup, conf=aconf)
    sup.add_worker()
    # The seeded crash-looper: SIGKILLs itself on its first stage of
    # every life; the preserved env makes every restart die the same
    # way until quarantine.
    sup.add_worker(wid="looper", extra_env={
        "SRT_FAULTS": "workerdeath@cluster.stage:1",
        "SRT_FAULTS_SEED": "7"})

    c0 = dict(faults.counters())
    lock = threading.Lock()
    done = [0]
    failures = []
    per_client = total // len(sessions)
    storm_at = per_client // 2
    storm_fired = threading.Event()

    def storm():
        """SIGKILL half the running fleet, supervisor heals it."""
        with sup._lock:
            running = [w for w in sup.workers.values()
                       if w.state == RUNNING and w.wid != "looper"
                       and w.proc.poll() is None]
        victims = running[:max(len(running) // 2, 1)]
        for w in victims:
            w.proc.kill()
        return [w.wid for w in victims]

    def client(k):
        s = sessions[k]
        for j in range(per_client):
            i = k * per_client + j
            if k == 0 and j == storm_at and not storm_fired.is_set():
                storm_fired.set()
                storm()
            df = _shape_q3(s, data_dir, i)
            try:
                rows = SC.collect_with_retry(df.collect, conf=s.conf,
                                             seed=k)
            except BaseException as e:  # pragma: no cover
                with lock:
                    failures.append((k, i, repr(e)))
                return
            with lock:
                done[0] += 1
                if rows != expected[i % N_SLOTS]:
                    failures.append((k, i, "diverged from solo run"))

    sup.start()
    scaler.start()
    try:
        threads = [threading.Thread(target=client, args=(k,),
                                    name=f"autoscale-soak-{k}")
                   for k in range(len(sessions))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(1800)
        assert failures == [], failures[:10]
        assert done[0] == total
        assert storm_fired.is_set()

        # Quiet period: the idle clock runs down and the fleet shrinks.
        deadline = time.monotonic() + 30
        while sup.active_count() > scaler.min_workers and \
                time.monotonic() < deadline:
            time.sleep(0.25)

        c1 = faults.counters()
        delta = lambda k: c1.get(k, 0) - c0.get(k, 0)
        # Self-healing invariant: workers run ONE stage at a time, so
        # every death (storm + crash-looper) costs AT MOST one stage
        # recompute; drains cost zero.
        assert delta("clusterWorkerDeaths") >= 1          # storm hit
        assert delta("stageRecomputes") <= \
            delta("clusterWorkerDeaths")
        # The storm actually healed: restarts happened and the pool
        # ended the soak serving from supervised workers.
        assert sup.counters["restarts"] >= 1
        # The crash-looper burned its budget into quarantine.
        assert "looper" in sup.quarantined()
        assert "crash-loop" in sup.quarantined()["looper"]
        assert sup.counters["quarantines"] == 1
        # The autoscaler visibly tracked load in the fleet event log:
        # scale-ups under the client hammer, scale-downs after.
        events = history.read_fleet_events(fleet_dir)
        kinds = [e["event"] for e in events]
        assert "autoscale-up" in kinds
        assert "autoscale-down" in kinds
        peak = max(e["workers"] for e in events)
        assert peak >= 2                       # it actually grew
        assert sup.active_count() <= peak      # ...and shrank back
        # Scale-downs drained cleanly: every retirement committed its
        # manifests first, so drains never show up as recomputes.
        assert sup.counters["drains"] >= 1
    finally:
        scaler.stop()
        sup.close()
