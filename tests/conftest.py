"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; all sharding logic is exercised on
``--xla_force_host_platform_device_count=8`` CPU devices (SURVEY.md §4's
"distributed without a cluster" strategy, re-imagined for JAX). Must run
before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

# The environment's sitecustomize registers a remote-TPU ("axon") PJRT plugin
# and points jax_platforms at it; initializing it costs a slow tunnel claim.
# Tests must be hermetic and CPU-only, so drop the plugin before any backend
# is materialized and pin the platform list back to cpu.
_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Acceptance hook: SRT_STAGE_FUSION=0 flips the stage-fusion default off
# for a whole test run, verifying every suite still passes with the
# unfused plan shape (spark.rapids.sql.stageFusion.enabled=false).
if os.environ.get("SRT_STAGE_FUSION") == "0":
    from spark_rapids_tpu import config as _C  # noqa: E402
    _C.STAGE_FUSION_ENABLED.default = False

# SRT_PIPELINE=0 is additionally honored dynamically by
# parallel/pipeline.py (params_of) — every suite must pass with the
# serial dispatch path. SRT_PIPELINE_PREFETCH overrides the default
# prefetch depth (the CI matrix runs prefetchPartitions=1 vs default).
if os.environ.get("SRT_PIPELINE_PREFETCH"):
    from spark_rapids_tpu import config as _C2  # noqa: E402
    _C2.PIPELINE_PREFETCH_PARTITIONS.default = int(
        os.environ["SRT_PIPELINE_PREFETCH"])


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _fault_state_isolation():
    """Snapshot + restore the process-global fault registry and recovery
    counters around EVERY test: a chaos test that arms a schedule (via
    faults.configure or a session conf collect) can no longer bleed an
    armed schedule or counter state into later tests, and an env-armed
    schedule (SRT_FAULTS) survives each test with exactly the state it
    entered with. The degraded batch target resets too — it is process
    state the OOM shrink rung leaks by design."""
    from spark_rapids_tpu import faults
    from spark_rapids_tpu.memory import oom
    state = faults.snapshot()
    yield
    faults.restore(state)
    oom.reset_degradation()
