"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; all sharding logic is exercised on
``--xla_force_host_platform_device_count=8`` CPU devices (SURVEY.md §4's
"distributed without a cluster" strategy, re-imagined for JAX). Must run
before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import jax._src.xla_bridge as _xb  # noqa: E402

# The environment's sitecustomize registers a remote-TPU ("axon") PJRT plugin
# and points jax_platforms at it; initializing it costs a slow tunnel claim.
# Tests must be hermetic and CPU-only, so drop the plugin before any backend
# is materialized and pin the platform list back to cpu.
_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Cost-based placement no longer needs an env kill-switch here: the
# estimator detects the CPU-only backend the suite runs on and zeroes
# the tunnel sync floor itself (plan/cost.py effective_sync_floor_ms),
# so mini-scale fixtures stay device-placed without production
# constants being misapplied. Placement behavior is covered by
# tests/test_cost.py via explicit conf keys; an SRT_COST in the
# environment (the CI no-cost-placement matrix entry) still wins.

# Acceptance hook: SRT_STAGE_FUSION=0 flips the stage-fusion default off
# for a whole test run, verifying every suite still passes with the
# unfused plan shape (spark.rapids.sql.stageFusion.enabled=false).
if os.environ.get("SRT_STAGE_FUSION") == "0":
    from spark_rapids_tpu import config as _C  # noqa: E402
    _C.STAGE_FUSION_ENABLED.default = False

# SRT_PIPELINE=0 is additionally honored dynamically by
# parallel/pipeline.py (params_of) — every suite must pass with the
# serial dispatch path. SRT_PIPELINE_PREFETCH overrides the default
# prefetch depth (the CI matrix runs prefetchPartitions=1 vs default).
if os.environ.get("SRT_PIPELINE_PREFETCH"):
    from spark_rapids_tpu import config as _C2  # noqa: E402
    _C2.PIPELINE_PREFETCH_PARTITIONS.default = int(
        os.environ["SRT_PIPELINE_PREFETCH"])


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def _map_count():
    try:
        with open("/proc/self/maps", "rb") as f:
            return sum(1 for _ in f)
    except OSError:       # non-Linux: no map table, no ceiling to dodge
        return 0


@pytest.fixture(autouse=True)
def _jit_map_pressure_relief():
    """Shed compiled executables before the kernel's mmap ceiling.

    A live XLA CPU executable for a real query kernel holds ~80 mmap
    regions and jax keeps every compiled program of the process alive,
    so a full single-process suite run accumulates memory maps
    monotonically; once the process crosses the kernel's
    vm.max_map_count ceiling (65530 by default) the next compile's mmap
    fails and XLA SIGSEGVs — the run dies at whatever test happens to
    compile there. Relief is tiered: first evict the OLDEST half of the
    engine's kernel cache (cold one-off kernels from earlier files; the
    current file's hot set survives, so there is no recompile storm),
    and only if the map table is still critical drop every jax cache
    (kernels recompile transparently — slow, but alive)."""
    yield
    import gc
    if _map_count() > 52000:
        from spark_rapids_tpu.ops import kernel_cache as kc
        cache = kc.cache()
        bound = cache.max_entries
        cache.configure(max(bound // 2, 64))
        cache.configure(bound)
        gc.collect()
        if _map_count() > 61000:
            import jax
            jax.clear_caches()
            gc.collect()


@pytest.fixture(autouse=True)
def _fault_state_isolation():
    """Snapshot + restore the process-global fault registry and recovery
    counters around EVERY test: a chaos test that arms a schedule (via
    faults.configure or a session conf collect) can no longer bleed an
    armed schedule or counter state into later tests, and an env-armed
    schedule (SRT_FAULTS) survives each test with exactly the state it
    entered with. The degraded batch target resets too — it is process
    state the OOM shrink rung leaks by design."""
    from spark_rapids_tpu import faults
    from spark_rapids_tpu.memory import oom
    state = faults.snapshot()
    yield
    faults.restore(state)
    oom.reset_degradation()


@pytest.fixture(autouse=True)
def _trace_ring_isolation():
    """Drop recorded flight-recorder events after every test so a traced
    test can never leak its ring contents (or query-id attribution) into
    a later test's assertions. Configuration (e.g. an env-armed
    SRT_TRACE=1 run) is left as-is — only the rings clear."""
    yield
    from spark_rapids_tpu import monitoring
    monitoring.reset()


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    """Clear the live-telemetry registry and event-log routing after
    every test: a metrics-enabled test must never leak counter values,
    fleet payloads, or a configured event-log directory into a later
    test's scrape/record assertions. The enabled flag itself is left
    as-is so an env-armed SRT_METRICS=1 matrix run (whole-suite
    acceptance) keeps recording test to test — only the values clear."""
    yield
    from spark_rapids_tpu.monitoring import history, telemetry
    telemetry.reset()
    history.set_dir("")


@pytest.fixture(autouse=True)
def _cost_calibration_isolation():
    """Reset the cost model's self-calibration state after every test: a
    traced collect feeds observed sync/throughput numbers into
    process-global effective constants (plan/cost.py observe_query),
    which must never skew a later test's placement assertions."""
    yield
    from spark_rapids_tpu.plan import cost
    cost.reset_calibration()
