"""Out-of-core grace hash joins (ops/join.py): a shuffled hash join
whose build side exceeds the device budget partitions BOTH sides by key
fingerprint into spillable buckets and joins co-partitioned bucket pairs
ON DEVICE — zero host fallbacks, bit-identical to the in-budget run,
including under seeded fault schedules. Also: the grace path is the OOM
escalation rung directly ABOVE host fallback (ops/base.py
execute_device_recovering)."""

import numpy as np
import pytest

from spark_rapids_tpu import FLOAT64, INT64
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.memory import oom
from spark_rapids_tpu.plan.logical import col


@pytest.fixture(autouse=True)
def clean_fault_state():
    faults.configure("")
    faults.reset_counters()
    oom.reset_degradation()
    yield
    faults.configure("")
    faults.reset_counters()
    oom.reset_degradation()


# The scheduler floors every managed query's catalog budget at 1 MiB,
# so "2x the device budget" means a >= 2 MiB build side: ~110k rows of
# (int64 key, float64 value) is ~2.6 MiB registered (incl. validity).
_N = 110_000
_KEYS = 30_000
_BUDGET = 1 << 20


def _data():
    rng = np.random.default_rng(42)
    left = {"k": rng.integers(0, _KEYS, _N).tolist(),
            "v": rng.normal(size=_N).tolist()}
    right = {"k": rng.integers(0, _KEYS, _N).tolist(),
             "w": rng.normal(size=_N).tolist()}
    return left, right


_LEFT, _RIGHT = _data()


def _run(budget, how="inner", chaos="", grace=True):
    s = TpuSession()
    s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
    s.set("spark.rapids.sql.aqe.replan.enabled", False)
    s.set("spark.rapids.sql.cost.enabled", False)
    s.set("spark.rapids.sql.shuffle.partitions", 1)
    s.set("spark.rapids.sql.test.faults", chaos)
    s.set("spark.rapids.sql.test.faults.seed", 7)
    s.set("spark.rapids.sql.retry.backoffMs", 1)
    s.set("spark.rapids.sql.join.grace.enabled", grace)
    if budget:
        s.set("spark.rapids.memory.tpu.budgetBytes", budget)
    left = s.create_dataframe(_LEFT, [("k", INT64), ("v", FLOAT64)],
                              num_partitions=4)
    right = s.create_dataframe(_RIGHT, [("k", INT64), ("w", FLOAT64)],
                               num_partitions=4)
    df = left.join(right, "k", how)
    rows = df.collect()
    mets = {}
    for key, m in df._physical().last_ctx.metrics.items():
        for name, v in m.values.items():
            if name in ("graceJoinPartitions", "graceJoinEngaged",
                        "hostFallbacks"):
                mets[name] = mets.get(name, 0) + v
    return rows, mets


def _assert_bit_identical(got, want):
    """Join outputs are gathers of the input values, so even the float
    columns must match bit-for-bit — only the emission ORDER may differ
    between the single-batch and bucketed paths."""
    assert sorted(map(repr, got)) == sorted(map(repr, want))


class TestGraceJoin:
    def test_build_side_2x_budget_runs_on_device(self):
        want, m0 = _run(None)
        assert m0.get("graceJoinPartitions", 0) == 0
        got, m1 = _run(_BUDGET)
        assert m1.get("graceJoinPartitions", 0) > 0, m1
        assert m1.get("hostFallbacks", 0) == 0, m1
        _assert_bit_identical(got, want)

    @pytest.mark.parametrize("how", [
        pytest.param("left", marks=pytest.mark.slow),
        "semi", "anti",
        pytest.param("full", marks=pytest.mark.slow),
    ])
    def test_join_types_bit_identical(self, how):
        want, _ = _run(None, how)
        got, m = _run(_BUDGET, how)
        assert m.get("graceJoinPartitions", 0) > 0, m
        assert m.get("hostFallbacks", 0) == 0, m
        _assert_bit_identical(got, want)

    @pytest.mark.parametrize("chaos", [
        "oom@upload:1,oom@kernel:1,oom@concat:1",
        pytest.param("transient@exchange.flush:1,oom@kernel:1",
                     marks=pytest.mark.slow),
        pytest.param("corrupt@wire:2,oom@upload:1",
                     marks=pytest.mark.slow),
    ])
    def test_grace_under_chaos_bit_identical(self, chaos):
        want, _ = _run(_BUDGET)
        faults.reset_counters()
        got, m = _run(_BUDGET, chaos=chaos)
        assert faults.counters().get("faultsInjected", 0) > 0
        assert m.get("graceJoinPartitions", 0) > 0, m
        assert m.get("hostFallbacks", 0) == 0, m
        _assert_bit_identical(got, want)

    def test_grace_disabled_still_correct(self):
        """Kill switch: with grace off the join still completes through
        the ladder (or plain execution) and matches, with zero grace
        buckets."""
        want, _ = _run(None)
        got, m = _run(_BUDGET, grace=False)
        assert m.get("graceJoinPartitions", 0) == 0
        _assert_bit_identical(got, want)


class TestGraceOomRung:
    def test_ladder_exhaustion_engages_grace_before_host(self,
                                                         monkeypatch):
        """OomRetryExhausted from the join's device path must retry
        through the grace-partitioned rung (graceJoinEngaged) — host
        fallback stays the LAST resort."""
        from spark_rapids_tpu.memory.oom import OomRetryExhausted
        from spark_rapids_tpu.ops.join import ShuffledHashJoinExec
        real = ShuffledHashJoinExec.execute_device

        def oom_until_grace(self, ctx, partition):
            if not ctx.cache.get(self._grace_force_key()):
                raise OomRetryExhausted(MemoryError("injected"),
                                        ["spill-all"])
            yield from real(self, ctx, partition)

        monkeypatch.setattr(ShuffledHashJoinExec, "execute_device",
                            oom_until_grace)
        want_rows = _run_small(grace_expected=True)
        monkeypatch.setattr(ShuffledHashJoinExec, "execute_device", real)
        plain = _run_small(grace_expected=False)
        _assert_bit_identical(want_rows, plain)


def _run_small(grace_expected: bool):
    rng = np.random.default_rng(5)
    n = 4_000
    s = TpuSession()
    s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
    s.set("spark.rapids.sql.aqe.replan.enabled", False)
    s.set("spark.rapids.sql.cost.enabled", False)
    s.set("spark.rapids.sql.shuffle.partitions", 1)
    left = s.create_dataframe(
        {"k": rng.integers(0, 500, n).tolist(),
         "v": rng.normal(size=n).tolist()},
        [("k", INT64), ("v", FLOAT64)], num_partitions=2)
    right = s.create_dataframe(
        {"k": rng.integers(0, 500, n).tolist(),
         "w": rng.normal(size=n).tolist()},
        [("k", INT64), ("w", FLOAT64)], num_partitions=2)
    df = left.join(right, "k", "inner")
    rows = df.collect()
    engaged = sum(
        m.values.get("graceJoinEngaged", 0) + m.values.get(
            "graceJoinPartitions", 0)
        for m in df._physical().last_ctx.metrics.values())
    if grace_expected:
        assert engaged > 0
        assert faults.counters().get("graceJoinEngaged", 0) > 0
    else:
        assert engaged == 0
    return rows
