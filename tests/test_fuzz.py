"""Fuzzed dual-engine parity tests (ref: the data_gen.py-driven
integration tests — every operator family is fed adversarial typed data
and the device plan must agree with the host oracle engine exactly).
"""

import math

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu import exprs as E
from spark_rapids_tpu.exprs.base import BoundReference as Ref
from spark_rapids_tpu.api import (
    TpuSession, agg_avg, agg_count, agg_max, agg_min, agg_sum, col)

from data_gen import (
    ALL_GENS, FLOAT_GENS, INTEGRAL_GENS, NUMERIC_GENS, BooleanGen,
    DateGen, DoubleGen, IntegerGen, LongGen, RepeatSeqGen, StringGen,
    binary_op_batch, gen_dict, unary_op_batch)
from harness import assert_rows_equal, check_expr, check_exprs


@pytest.fixture
def session():
    return TpuSession({
        "spark.rapids.sql.variableFloatAgg.enabled": True,
        "spark.rapids.sql.incompatibleOps.enabled": True,
    })


def dual_collect(df, approx_float=False):
    dev, host = df.collect(), df.collect_host()
    keyf = lambda r: tuple((v is None, str(v)) for v in r)
    dev, host = sorted(dev, key=keyf), sorted(host, key=keyf)
    assert_rows_equal(dev, host, approx_float, "device vs host engine")
    return dev


class TestFuzzedExpressions:
    @pytest.mark.parametrize("seed", [1, 2, 3])
    @pytest.mark.parametrize("gen", NUMERIC_GENS,
                             ids=lambda g: g.dtype.name)
    def test_arithmetic(self, gen, seed):
        b = binary_op_batch(gen, n=96, seed=seed)
        t = gen.dtype
        check_exprs([E.Add(Ref(0, t), Ref(1, t)),
                     E.Subtract(Ref(0, t), Ref(1, t)),
                     E.Multiply(Ref(0, t), Ref(1, t))], b)

    @pytest.mark.parametrize("seed", [1, 2])
    @pytest.mark.parametrize("gen", ALL_GENS, ids=lambda g: g.dtype.name)
    def test_comparisons(self, gen, seed):
        b = binary_op_batch(gen, n=96, seed=seed)
        t = gen.dtype
        check_exprs([E.EqualTo(Ref(0, t), Ref(1, t)),
                     E.LessThan(Ref(0, t), Ref(1, t)),
                     E.GreaterThanOrEqual(Ref(0, t), Ref(1, t)),
                     E.IsNull(Ref(0, t))], b)

    @pytest.mark.parametrize("seed", [1, 2])
    def test_string_ops(self, seed):
        b = unary_op_batch(StringGen(), n=96, seed=seed)
        check_exprs([E.Upper(Ref(0, dt.STRING)),
                     E.Lower(Ref(0, dt.STRING)),
                     E.Length(Ref(0, dt.STRING)),
                     E.StringTrim(Ref(0, dt.STRING)),
                     E.StringReverse(Ref(0, dt.STRING))], b)

    @pytest.mark.parametrize("gen", FLOAT_GENS, ids=lambda g: g.dtype.name)
    def test_float_predicates(self, gen):
        b = binary_op_batch(gen, n=128, seed=5)
        t = gen.dtype
        check_exprs([E.IsNan(Ref(0, t)),
                     E.EqualTo(Ref(0, t), Ref(1, t)),
                     E.LessThan(Ref(0, t), Ref(1, t))], b)

    @pytest.mark.parametrize("gen", INTEGRAL_GENS,
                             ids=lambda g: g.dtype.name)
    def test_murmur3(self, gen):
        b = unary_op_batch(gen, n=96, seed=9)
        check_expr(E.Murmur3Hash([Ref(0, gen.dtype)]), b)

    def test_date_parts(self):
        b = unary_op_batch(DateGen(), n=96, seed=3)
        check_exprs([E.Year(Ref(0, dt.DATE)), E.Month(Ref(0, dt.DATE)),
                     E.DayOfMonth(Ref(0, dt.DATE)),
                     E.DayOfWeek(Ref(0, dt.DATE)),
                     E.Quarter(Ref(0, dt.DATE)),
                     E.TruncDate(Ref(0, dt.DATE), "month")], b)

    @pytest.mark.parametrize("gen", ALL_GENS, ids=lambda g: g.dtype.name)
    def test_cast_to_string(self, gen):
        if gen.dtype.is_floating:
            pytest.skip("float->string formatting compared in test_exprs")
        b = unary_op_batch(gen, n=64, seed=11)
        check_expr(E.Cast(Ref(0, gen.dtype), dt.STRING), b)


class TestFuzzedAggregates:
    @pytest.mark.parametrize("seed", [1, 2])
    def test_groupby_int_keys(self, session, seed):
        schema, data = gen_dict(
            [("k", RepeatSeqGen(IntegerGen(), length=6)),
             ("v", LongGen(special_prob=0.05)),
             ("x", DoubleGen())], n=200, seed=seed)
        # Clamp longs so sums cannot overflow differently per merge order.
        data["v"] = [None if v is None else v % 10 ** 12 for v in data["v"]]
        df = session.create_dataframe(data, schema, num_partitions=3)
        dual_collect(df.group_by("k").agg(
            agg_count().alias("n"),
            agg_sum(col("v")).alias("sv"),
            agg_min(col("x")).alias("mn"),
            agg_max(col("x")).alias("mx")), approx_float=True)

    def test_groupby_string_keys(self, session):
        schema, data = gen_dict(
            [("k", RepeatSeqGen(StringGen(), length=5)),
             ("v", IntegerGen())], n=150, seed=4)
        df = session.create_dataframe(data, schema, num_partitions=2)
        dual_collect(df.group_by("k").agg(
            agg_count(col("v")).alias("nv"),
            agg_min(col("v")).alias("mn"),
            agg_max(col("v")).alias("mx")))

    def test_global_agg_bools_dates(self, session):
        schema, data = gen_dict(
            [("b", BooleanGen()), ("d", DateGen())], n=120, seed=8)
        df = session.create_dataframe(data, schema, num_partitions=3)
        dual_collect(df.agg(agg_count(col("b")).alias("nb"),
                            agg_min(col("d")).alias("mnd"),
                            agg_max(col("d")).alias("mxd")))


class TestFuzzedJoins:
    @pytest.mark.parametrize("join_type", ["inner", "left", "semi", "anti"])
    def test_join_fuzzed_keys(self, session, join_type):
        schema_l, data_l = gen_dict(
            [("k", RepeatSeqGen(IntegerGen(), length=7, seed=3)),
             ("lv", IntegerGen())], n=90, seed=1)
        schema_r, data_r = gen_dict(
            [("k", RepeatSeqGen(IntegerGen(), length=7, seed=3)),
             ("rv", IntegerGen())], n=70, seed=2)
        lhs = session.create_dataframe(data_l, schema_l, num_partitions=2)
        data_r = {"k2": data_r["k"], "rv": data_r["rv"]}
        rhs = session.create_dataframe(
            data_r, [("k2", schema_r[0][1]), ("rv", schema_r[1][1])],
            num_partitions=2)
        out = lhs.join_on(rhs, ["k"], ["k2"], how=join_type)
        dual_collect(out)

    def test_join_float_keys_nan_zero(self, session):
        # NaN==NaN and -0.0==0.0 for join keys (Spark semantics).
        data_l = {"k": [float("nan"), -0.0, 1.5, None],
                  "lv": [1, 2, 3, 4]}
        data_r = {"k2": [float("nan"), 0.0, 2.5, None],
                  "rv": [10, 20, 30, 40]}
        lhs = session.create_dataframe(
            data_l, [("k", dt.FLOAT64), ("lv", dt.INT32)])
        rhs = session.create_dataframe(
            data_r, [("k2", dt.FLOAT64), ("rv", dt.INT32)])
        out = dual_collect(lhs.join_on(rhs, ["k"], ["k2"], how="inner"))
        assert len(out) == 2   # NaN pair + zero pair; NULL never matches


class TestFuzzedSort:
    @pytest.mark.parametrize("gen", ALL_GENS, ids=lambda g: g.dtype.name)
    def test_sort_every_type(self, session, gen):
        schema, data = gen_dict(
            [("k", gen), ("i", IntegerGen(nullable=False))],
            n=80, seed=6)
        data["i"] = list(range(80))     # unique tiebreaker
        df = session.create_dataframe(data, schema, num_partitions=2)
        out_dev = df.order_by(col("k").asc(), col("i").asc()).collect()
        out_host = df.order_by(col("k").asc(),
                               col("i").asc()).collect_host()
        assert_rows_equal(out_dev, out_host, False, "sorted device vs host")

    def test_sort_desc_floats(self, session):
        schema, data = gen_dict(
            [("x", DoubleGen()), ("i", IntegerGen(nullable=False))],
            n=80, seed=2)
        data["i"] = list(range(80))
        df = session.create_dataframe(data, schema, num_partitions=3)
        a = df.order_by(col("x").desc(), col("i").asc()).collect()
        b = df.order_by(col("x").desc(), col("i").asc()).collect_host()
        assert_rows_equal(a, b, False, "desc sort device vs host")
