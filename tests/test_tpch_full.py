"""All 22 TPC-H queries, device engine vs the pandas oracle
(TpchLikeSpark.scala:293-onward parity — VERDICT r4 item 4).

Each query runs through the full planner/device pipeline on the CPU
backend at a small scale factor and must match the independent pandas
implementation (ordered compare unless the query sorts by a computed
float — benchmarks/tpch.py check_result)."""

import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpch22")
    tpch.generate(str(d), scale=0.01, files_per_table=2)
    return str(d)


@pytest.mark.parametrize("qn", sorted(tpch.QUERIES,
                                      key=lambda q: int(q[1:])))
def test_query_matches_pandas(qn, data_dir):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.hasNans", False)
    got = tpch.QUERIES[qn](s, data_dir).collect()
    want = tpch.pandas_query(qn, data_dir)
    assert tpch.check_result(qn, got, want), (
        f"{qn}: device result diverges from pandas oracle\n"
        f"  got[:3]={got[:3]}\n  want[:3]={want[:3]}")
