"""Whole-stage fusion + process-global kernel cache (the serving story:
repeated execution pays compile cost exactly once).

Covers: the Project->Filter->Project chain compiling as ONE fused kernel,
the retrace-regression guarantee (a repeated TPC-H query through a FRESH
planner reports zero kernel-cache misses), the stageFusion.enabled kill
switch restoring the unfused plan shape, stage breaks at non-fusible
operators, LocalLimit budget threading inside a fused stage, and the
explain/pretty_tree/metrics rendering of fused stages."""

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.ops import kernel_cache as kc
from spark_rapids_tpu.ops.fused import FusedStageExec
from spark_rapids_tpu.plan.logical import agg_sum, col


def _chain_df(s: TpuSession):
    df = s.create_dataframe(
        {"k": [1, 2, 3, 4, 5, 6], "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]},
        [("k", srt.INT64), ("v", srt.FLOAT64)], num_partitions=2)
    return df.select((col("v") * 2).alias("v2"), "k") \
             .filter(col("v2") > 2.0) \
             .select((col("v2") + 1).alias("v3"), "k")


def _find(node, cls):
    out = []

    def rec(n):
        if isinstance(n, cls):
            out.append(n)
        for c in n.children:
            rec(c)
    rec(node)
    return out


class TestFusionShape:
    def test_project_filter_project_fuses_to_one_stage(self):
        q = _chain_df(TpuSession())
        phys = q._physical()
        fused = _find(phys.root, FusedStageExec)
        assert len(fused) == 1
        assert len(fused[0].ops) == 3
        names = [type(o).__name__ for o in fused[0].ops]
        assert sorted(names) == ["FilterExec", "ProjectExec",
                                 "ProjectExec"]
        # No standalone Project/Filter execs remain in the device plan.
        from spark_rapids_tpu.ops.basic import FilterExec, ProjectExec
        assert not _find(phys.root, ProjectExec)
        assert not _find(phys.root, FilterExec)

    def test_chain_compiles_as_single_kernel(self):
        """A fusible 3-op chain executes as ONE jitted kernel — the cache
        sees exactly one fused-stage program and zero per-op project or
        filter programs."""
        kc.cache().clear()
        q = _chain_df(TpuSession())
        got = sorted(q.collect())
        assert got == sorted(q.collect_host())
        kinds = {k[0] for k in kc.cache().keys()}
        assert "fused-stage" in kinds
        assert "project" not in kinds and "filter" not in kinds
        fused_keys = [k for k in kc.cache().keys()
                      if k[0] == "fused-stage"]
        assert len(fused_keys) == 1

    def test_gate_off_restores_unfused_plan(self):
        from spark_rapids_tpu.ops.basic import FilterExec, ProjectExec
        s = TpuSession()
        s.set("spark.rapids.sql.stageFusion.enabled", False)
        q = _chain_df(s)
        phys = q._physical()
        assert not _find(phys.root, FusedStageExec)
        assert _find(phys.root, ProjectExec)
        assert _find(phys.root, FilterExec)
        assert sorted(q.collect()) == sorted(q.collect_host())

    def test_stage_breaks_at_aggregate(self):
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        df = s.create_dataframe(
            {"k": [1, 1, 2, 2], "v": [1.0, 2.0, 3.0, 4.0]},
            [("k", srt.INT64), ("v", srt.FLOAT64)])
        # filter -> project below the agg; project above it: two fusible
        # regions separated by the aggregate, neither long enough alone
        # except the lower one (2 ops).
        q = df.filter(col("v") > 1.0) \
              .select("k", (col("v") * 10).alias("w")) \
              .group_by("k").agg(agg_sum(col("w")).alias("sw"))
        phys = q._physical()
        fused = _find(phys.root, FusedStageExec)
        assert len(fused) == 1          # the filter+project pair
        assert len(fused[0].ops) == 2
        got = dict(q.collect())
        assert got == {1: 20.0, 2: 70.0}

    def test_contextual_exprs_do_not_fuse(self):
        from spark_rapids_tpu.plan.logical import spark_partition_id
        s = TpuSession()
        df = s.create_dataframe(
            {"v": [1.0, 2.0, 3.0]}, [("v", srt.FLOAT64)])
        q = df.select((col("v") * 2).alias("v2")) \
              .with_column("p", spark_partition_id())
        phys = q._physical()
        # The contextual projection stays unfused (needs EvalContext).
        for f in _find(phys.root, FusedStageExec):
            for op in f.ops:
                from spark_rapids_tpu.exprs.nondeterministic import \
                    needs_eval_context
                assert not needs_eval_context(getattr(op, "exprs", []))
        assert sorted(q.collect()) == [(2.0, 0), (4.0, 0), (6.0, 0)]

    def test_local_limit_budget_threads_through_fusion(self):
        """LocalLimit inside a fused stage keeps its per-partition budget
        across batches (traced carry, no host sync)."""
        s = TpuSession()
        df = s.create_dataframe(
            {"v": list(range(20))}, [("v", srt.INT64)])
        q = df.select((col("v") * 1).alias("v")) \
              .filter(col("v") >= 0).limit(5)
        assert len(q.collect()) == 5


class TestRetraceRegression:
    @pytest.fixture(scope="class")
    def tpch_dir(self, tmp_path_factory):
        from spark_rapids_tpu.benchmarks import tpch
        d = str(tmp_path_factory.mktemp("tpch_fusion"))
        tpch.generate(d, scale=0.002, files_per_table=2, seed=11)
        return d

    def _session(self):
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        # Retrace regression counts DEVICE kernel compiles; the cost
        # model would host-place these mini-scale queries.
        s.set("spark.rapids.sql.cost.enabled", False)
        return s

    @pytest.mark.parametrize("qname", ["q6", "q1"])
    def test_second_run_has_zero_cache_misses(self, qname, tpch_dir):
        """The retrace-regression guarantee: running the SAME TPC-H query
        twice through a fresh planner/session compiles nothing on the
        second run — every kernel lookup hits the process-global cache."""
        from spark_rapids_tpu.benchmarks import tpch
        first = tpch.QUERIES[qname](self._session(), tpch_dir).collect()
        kc.cache().reset_stats()
        second = tpch.QUERIES[qname](self._session(), tpch_dir).collect()
        stats = kc.cache().stats()
        assert stats["misses"] == 0, (
            f"{qname} second run re-traced kernels: {stats}; "
            f"keys={kc.cache().keys()}")
        assert stats["hits"] > 0
        assert tpch.rows_close(sorted(first), sorted(second))


class TestObservability:
    def test_explain_and_pretty_tree_render_fused_stage(self):
        q = _chain_df(TpuSession())
        phys = q._physical()
        tree = phys.root.pretty_tree()
        assert "FusedStageExec[ProjectExec->FilterExec->ProjectExec]" \
            in tree
        report = phys.explain()
        assert "Fused stages: 1" in report
        assert "fuses [ProjectExec, FilterExec, ProjectExec]" in report

    def test_fused_metrics_owner_and_cache_counters(self):
        q = _chain_df(TpuSession())
        q.collect()
        m = q.metrics()
        fused_key = next(k for k in m if k.startswith("FusedStageExec["))
        vals = m[fused_key]
        assert vals.get("numFusedStages") == 1
        assert vals.get("numFusedOps") == 3
        assert vals.get("numOutputBatches", 0) >= 1
        hits = vals.get("kernelCacheHits", 0)
        misses = vals.get("kernelCacheMisses", 0)
        assert hits + misses >= 1
        if misses:     # a fresh compile surfaces its compile time
            assert vals.get("compileTime", 0) > 0

    def test_cache_lru_bound_evicts(self):
        cache = kc.KernelCache(max_entries=2)
        for i in range(4):
            cache.get(("k", i), lambda: i)
        st = cache.stats()
        assert st["entries"] == 2 and st["evictions"] == 2

    def test_kernel_cache_max_entries_conf(self):
        s = TpuSession()
        s.set("spark.rapids.sql.kernelCache.maxEntries", 7)
        _chain_df(s)._physical()
        assert kc.cache().max_entries == 7
        # Restore the default for the rest of the suite.
        s2 = TpuSession()
        _chain_df(s2)._physical()
        from spark_rapids_tpu import config as C
        assert kc.cache().max_entries == C.KERNEL_CACHE_MAX_ENTRIES.default
