"""Multi-query admission control, isolation, and cancellation (ISSUE 5;
parallel/scheduler.py).

The contracts under test:

- N concurrent TPC-H queries return results BIT-IDENTICAL to their solo
  runs (no cross-query state bleed through the semaphore, catalogs,
  kernel cache, or fault registry).
- A query cancelled mid-pipeline unwinds with QueryCancelledError,
  frees every buffer it owned (catalog leak report EMPTY), and leaves
  subsequent queries unaffected.
- Admission sheds load: a full run queue rejects immediately; a queued
  query past the admission timeout rejects with the timeout reason.
- Cross-query fault containment: a seeded fault injected into query A
  (``kind@site/query=N`` arming) recovers inside A while query B's
  results AND recovery counters are identical to a solo run.
"""

import threading
import time

import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.faults import QueryCancelledError
from spark_rapids_tpu.memory import oom
from spark_rapids_tpu.parallel import scheduler as SC
from spark_rapids_tpu.parallel.scheduler import (
    QueryManager, QueryRejectedError)


@pytest.fixture(autouse=True)
def clean_state():
    faults.configure("")
    faults.reset_counters()
    SC.reset_counters()
    oom.reset_degradation()
    yield
    faults.configure("")
    faults.reset_counters()
    SC.reset_counters()
    oom.reset_degradation()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_sched"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=11)
    return d


def _session(tag=None, chaos="", max_concurrent=4):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.scheduler.maxConcurrentQueries",
          max_concurrent)
    # The registry is process-global; every session (dis)arms
    # explicitly so the solo baselines never inherit a schedule.
    s.set("spark.rapids.sql.test.faults", chaos)
    s.set("spark.rapids.sql.test.faults.seed", 11)
    s.set("spark.rapids.sql.retry.backoffMs", 1)
    if chaos:
        # The device scan cache can serve batches a previous (baseline)
        # run uploaded, silently skipping the upload fault site — chaos
        # sessions always exercise the full dispatch funnel.
        s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    if tag is not None:
        s.set("spark.rapids.sql.test.faults.queryTag", tag)
    return s


QUERIES = ["q1", "q3", "q6"]


@pytest.fixture(scope="module")
def baselines(data_dir):
    out = {}
    for qn in QUERIES:
        out[qn] = tpch.QUERIES[qn](_session(), data_dir).collect()
    return out


# ---------------------------------------------------------------------------
# Concurrent bit-identity
# ---------------------------------------------------------------------------

def test_concurrent_queries_bit_identical(data_dir, baselines):
    """N threads x TPC-H q1/q3/q6 at once: every result equals its solo
    run exactly (tuple equality — floats by value)."""
    results = {}
    errors = {}

    def run(qn):
        try:
            results[qn] = tpch.QUERIES[qn](_session(), data_dir).collect()
        except BaseException as e:       # pragma: no cover - diagnostics
            errors[qn] = e

    threads = [threading.Thread(target=run, args=(qn,)) for qn in QUERIES]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors, errors
    for qn in QUERIES:
        assert results[qn] == baselines[qn], \
            f"{qn} diverged under concurrency"


def test_concurrent_soak_repeated_rounds(data_dir, baselines):
    """Short soak: several rounds of concurrent q1/q3/q6 stay
    bit-identical (kernel cache, scan cache, catalogs and scheduler
    state survive reuse)."""
    for _ in range(3):
        test_concurrent_queries_bit_identical(data_dir, baselines)


# ---------------------------------------------------------------------------
# Admission control: rejection + timeout + serial degenerate mode
# ---------------------------------------------------------------------------

def test_queue_full_rejects_immediately():
    mgr = QueryManager(max_concurrent=1, queue_depth=1,
                       admission_timeout_ms=60000)
    first = mgr.admit()
    waiter_ticket = {}
    started = threading.Event()

    def queued_waiter():
        started.set()
        waiter_ticket["t"] = mgr.admit()    # occupies the 1-deep queue

    t = threading.Thread(target=queued_waiter, daemon=True)
    t.start()
    started.wait(5)
    deadline = time.monotonic() + 5
    while mgr.queued_count < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    with pytest.raises(QueryRejectedError, match="queue full"):
        mgr.admit()                         # queue full: shed NOW
    mgr.finish(first)                       # waiter gets the slot
    t.join(10)
    assert "t" in waiter_ticket
    mgr.finish(waiter_ticket["t"])
    assert SC.counters().get("rejected", 0) >= 1


def test_admission_timeout_rejects():
    mgr = QueryManager(max_concurrent=1, queue_depth=4,
                       admission_timeout_ms=80)
    first = mgr.admit()
    t0 = time.monotonic()
    with pytest.raises(QueryRejectedError, match="timeout"):
        mgr.admit()
    assert time.monotonic() - t0 >= 0.06
    mgr.finish(first)
    second = mgr.admit()                    # slot free again: admitted
    mgr.finish(second)


def test_queue_full_rejection_e2e(data_dir, baselines):
    """End to end: with the only run slot held, a collect with a
    zero-depth queue sheds with QueryRejectedError instead of queuing —
    and succeeds once the slot frees."""
    s = _session()
    s.set("spark.rapids.sql.scheduler.maxConcurrentQueries", 1)
    s.set("spark.rapids.sql.scheduler.queueDepth", 0)
    s.set("spark.rapids.sql.scheduler.admissionTimeoutMs", 200)
    df = tpch.QUERIES["q6"](s, data_dir)
    mgr = SC.get_query_manager(s.conf)
    assert mgr.max_concurrent == 1
    hog = mgr.admit()
    try:
        with pytest.raises(QueryRejectedError):
            df.collect()
    finally:
        mgr.finish(hog)
    assert df.collect() == baselines["q6"]


def test_serial_mode_matches_baseline(data_dir, baselines):
    """maxConcurrentQueries=1 (the SRT_SCHEDULER_MAX_CONCURRENT=1 CI
    matrix degenerate): results byte-identical to the default run."""
    got = tpch.QUERIES["q1"](_session(max_concurrent=1),
                             data_dir).collect()
    assert got == baselines["q1"]


# ---------------------------------------------------------------------------
# Cancellation + deadlines
# ---------------------------------------------------------------------------

def test_cancel_mid_flight_frees_everything(data_dir, baselines):
    """Cancel a query wedged on an injected stall: it unwinds with
    QueryCancelledError (no retry), the catalog leak report is EMPTY
    (teardown freed every owned buffer), and the next query on the same
    process is unaffected."""
    s = _session(tag=1, chaos="stall@exchange.serve/query=1:1")
    df = tpch.QUERIES["q3"](s, data_dir)
    handle = df.submit()
    # Wait until the query is actually running (admitted), then cancel.
    deadline = time.monotonic() + 30
    while SC.get_query_manager().active_count < 1 \
            and time.monotonic() < deadline:
        time.sleep(0.01)
    time.sleep(0.1)                   # let it reach the stalled dispatch
    handle.cancel()
    with pytest.raises(QueryCancelledError):
        handle.result(60)
    ctx = df._physical().last_ctx
    assert ctx is not None and ctx.last_leak_report == [], \
        f"cancelled query leaked buffers: {ctx.last_leak_report}"
    assert SC.get_query_manager().active_count == 0
    # Counters: the teardown recorded the cancel, not a deadline kill.
    assert SC.counters().get("cancelled", 0) >= 1
    assert SC.counters().get("deadlineKills", 0) == 0
    # Subsequent queries are unaffected (slot released, registry sane).
    got = tpch.QUERIES["q6"](_session(), data_dir).collect()
    assert got == baselines["q6"]


def test_collect_timeout_deadline_kills(data_dir, baselines):
    """collect(timeout_ms=...) on a stalled query unwinds with the
    deadline reason, bumps deadlineKills, and leaks nothing."""
    s = _session(tag=3, chaos="stall@upload/query=3:1")
    df = tpch.QUERIES["q6"](s, data_dir)
    t0 = time.monotonic()
    with pytest.raises(QueryCancelledError, match="deadline"):
        df.collect(timeout_ms=300)
    assert time.monotonic() - t0 < faults.STALL_TIMEOUT_S
    ctx = df._physical().last_ctx
    assert ctx is not None and ctx.last_leak_report == []
    assert SC.counters().get("deadlineKills", 0) >= 1
    assert tpch.QUERIES["q6"](_session(), data_dir).collect() \
        == baselines["q6"]


def test_cancel_while_queued(data_dir):
    """A query still waiting for admission cancels cleanly (never runs,
    never leaks a slot)."""
    mgr = SC.get_query_manager(_session(max_concurrent=1).conf)
    assert mgr.max_concurrent == 1
    hog = mgr.admit()
    try:
        df = tpch.QUERIES["q6"](_session(max_concurrent=1), data_dir)
        handle = df.submit()
        deadline = time.monotonic() + 10
        while mgr.queued_count < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert mgr.queued_count == 1
        handle.cancel()
        with pytest.raises(QueryCancelledError):
            handle.result(30)
    finally:
        mgr.finish(hog)
    assert mgr.queued_count == 0
    assert mgr.active_count == 0


# ---------------------------------------------------------------------------
# Cross-query fault containment (the chaos matrix entry)
# ---------------------------------------------------------------------------

def _recovery_counters(df):
    m = df.metrics().get("Recovery@query", {})
    return {k: v for k, v in m.items() if v}


def test_cross_query_fault_containment(data_dir, baselines):
    """4 concurrent queries under a seeded PER-QUERY fault schedule:
    oom + stall + lostoutput chaos scoped to query A only
    (kind@site/query=1; the watchdog kills A's stall, lineage recovery
    recomputes A's lost stage). All four return results bit-identical
    to their solo runs; A's recovery counters show real injections; the
    three unfaulted neighbors' recovery counters are ZERO — the fault
    never crossed the isolation boundary."""
    chaos = ("oom@upload/query=1:1,stall@kernel/query=1:1,"
             "lostoutput@exchange.serve/query=1:1")
    plan = [("A", 1, "q3"), ("B", 2, "q6"), ("C", 3, "q1"),
            ("D", 4, "q6")]
    results, errors, dfs = {}, {}, {}

    barrier = threading.Barrier(len(plan), timeout=60)

    def run(name, tag, qn):
        try:
            s = _session(tag=tag, chaos=chaos)
            # Watchdog so A's injected stall is killed + re-dispatched
            # instead of sitting out the stall safety timeout; the
            # deadline is far above any healthy partition here.
            s.set("spark.rapids.sql.watchdog.enabled", True)
            s.set("spark.rapids.sql.watchdog.taskTimeoutMs", 4000)
            s.set("spark.rapids.sql.watchdog.maxAttempts", 3)
            df = tpch.QUERIES[qn](s, data_dir)
            dfs[name] = df
            barrier.wait()      # all four queries in flight together
            results[name] = df.collect()
        except BaseException as e:       # pragma: no cover - diagnostics
            errors[name] = e

    threads = [threading.Thread(target=run, args=args) for args in plan]
    for t in threads:
        t.start()
    for t in threads:
        t.join(180)
    assert not errors, errors
    for name, _, qn in plan:
        assert results[name] == baselines[qn], \
            f"query {name} ({qn}) diverged from its solo run"
    # A recovered from real injections; B/C/D never saw a single one.
    a_rec = _recovery_counters(dfs["A"])
    assert a_rec.get("faultsInjected", 0) > 0, a_rec
    for name in ("B", "C", "D"):
        rec = _recovery_counters(dfs[name])
        assert rec == {}, \
            f"query {name}'s isolation was breached: {rec}"


def test_query_scoped_faults_do_not_fire_for_other_tags(data_dir,
                                                        baselines):
    """A /query=N entry armed process-wide stays invisible to a query
    with a different tag even run SERIALLY (the containment is tag
    matching, not timing luck)."""
    chaos = "oom@upload/query=7:1"
    df = tpch.QUERIES["q6"](_session(tag=8, chaos=chaos), data_dir)
    assert df.collect() == baselines["q6"]
    assert _recovery_counters(df) == {}
    # Same spec, matching tag: it fires and recovers.
    faults.configure("")        # fresh arming for the same (spec, seed)
    df2 = tpch.QUERIES["q6"](_session(tag=7, chaos=chaos), data_dir)
    assert df2.collect() == baselines["q6"]
    assert _recovery_counters(df2).get("faultsInjected", 0) > 0


# ---------------------------------------------------------------------------
# Isolation plumbing units
# ---------------------------------------------------------------------------

def test_owner_tagging_and_fair_share(data_dir):
    """An admitted query's catalog carries its query id as the buffer
    owner tag, and queryMemoryFraction scales its device budget."""
    s = _session()
    s.set("spark.rapids.sql.scheduler.queryMemoryFraction", 0.5)
    s.set("spark.rapids.memory.tpu.budgetBytes", 1 << 24)
    df = tpch.QUERIES["q3"](s, data_dir)
    df.collect()
    ctx = df._physical().last_ctx
    assert ctx.query is not None
    # Catalog was rebuilt per query; budget got the 0.5 fair share.
    # (The catalog is closed by teardown; check the recorded leak
    # report instead of live state — it must be empty.)
    assert ctx.last_leak_report == []


def test_fault_spec_query_grammar():
    specs = faults.parse_spec("oom@upload/query=3:2,stall@kernel:1")
    assert specs[0].query == 3 and specs[0].count == 2
    assert specs[0].site == "upload"
    assert specs[1].query is None
    with pytest.raises(faults.FaultParseError):
        faults.parse_spec("oom@upload/quer=3")
    with pytest.raises(faults.FaultParseError):
        faults.parse_spec("oom@upload/query=x")


def test_cross_query_eviction_rung():
    """The OOM ladder's evict-neighbors rung spills OTHER queries'
    catalogs (offender's own buffers already went in rungs 1-2)."""
    from spark_rapids_tpu.memory.stores import BufferCatalog
    from tests.test_memory import make_batch
    mgr = QueryManager(max_concurrent=4)
    ta = mgr.admit()
    tb = mgr.admit()

    class FakeCtx:
        _catalog = BufferCatalog(device_budget_bytes=1 << 24)
    mgr.register_context(tb, FakeCtx())
    FakeCtx._catalog.add_batch(make_batch(64))
    assert FakeCtx._catalog.device_bytes > 0
    freed = mgr.evict_neighbors(ta.query_id)
    assert freed > 0
    assert FakeCtx._catalog.device_bytes == 0   # spilled to host tier
    assert mgr.evict_neighbors(tb.query_id) == 0  # own catalog skipped
    assert SC.counters().get("crossQueryEvictions", 0) >= 1
    mgr.finish(ta)
    mgr.finish(tb)
    FakeCtx._catalog.close()


# ---------------------------------------------------------------------------
# Retry-hint contract: every load rejection carries retry_after_ms
# (ISSUE 18 satellite — the deadline-unmeetable kind used to ship None
# even when only the load-scaled slack made it unmeetable)
# ---------------------------------------------------------------------------

def _qos_mgr(max_concurrent=1, queue_depth=0, timeout_ms=80):
    from spark_rapids_tpu.parallel import qos as Q
    return QueryManager(max_concurrent=max_concurrent,
                        queue_depth=queue_depth,
                        admission_timeout_ms=timeout_ms,
                        qos=Q.QosPolicy("8,3,1", 8))


def _qos_conf(**over):
    from spark_rapids_tpu.api.dataframe import TpuSession
    s = TpuSession()
    s.set("spark.rapids.sql.scheduler.qos.enabled", True)
    for k, v in over.items():
        s.set(k, v)
    return s.conf


def test_hint_on_queue_full_scales_with_depth():
    mgr = _qos_mgr(queue_depth=0)
    hog = mgr.admit()
    with pytest.raises(QueryRejectedError) as ei:
        mgr.admit()
    assert ei.value.kind == "queue-full"
    assert ei.value.retry_after_ms is not None
    assert ei.value.retry_after_ms >= 50.0
    mgr.finish(hog)


def test_hint_on_admission_timeout():
    mgr = _qos_mgr(queue_depth=4, timeout_ms=60)
    hog = mgr.admit()
    with pytest.raises(QueryRejectedError) as ei:
        mgr.admit()
    assert ei.value.kind == "admission-timeout"
    assert ei.value.retry_after_ms is not None and \
        ei.value.retry_after_ms > 0
    mgr.finish(hog)


def test_hint_on_tenant_quota():
    mgr = _qos_mgr(max_concurrent=4, queue_depth=4)
    conf = _qos_conf(**{
        "spark.rapids.sql.scheduler.qos.tenantMaxInFlight": 1})
    first = mgr.admit(conf, tenant="acme")
    with pytest.raises(QueryRejectedError) as ei:
        mgr.admit(conf, tenant="acme")
    assert ei.value.kind == "tenant-quota"
    assert ei.value.retry_after_ms is not None and \
        ei.value.retry_after_ms > 0
    mgr.finish(first)


def test_hint_on_deadline_unmeetable_load_scaled_vs_hopeless():
    """A deadline only the load-scaled slack breaks can succeed on
    resubmission (a drained queue shrinks the slack): hint carried. A
    deadline the RAW cost estimate already exceeds can never succeed
    as-is: hint None — collect_with_retry re-raises immediately."""
    mgr = _qos_mgr(max_concurrent=4, queue_depth=4)
    conf = _qos_conf(**{
        "spark.rapids.sql.scheduler.qos.deadlineAdmission.enabled": True,
        "spark.rapids.sql.scheduler.qos.deadlineSlack": 2.0})
    # cost 80 <= deadline 100, but 80 * 2.0 slack = 160 > 100.
    with pytest.raises(QueryRejectedError) as ei:
        mgr.admit(conf, cost_ms=80.0, deadline_ms=100.0)
    assert ei.value.kind == "deadline-unmeetable"
    assert ei.value.retry_after_ms is not None and \
        ei.value.retry_after_ms > 0
    # cost 300 > deadline 100 raw: hopeless, no hint.
    with pytest.raises(QueryRejectedError) as ei:
        mgr.admit(conf, cost_ms=300.0, deadline_ms=100.0)
    assert ei.value.kind == "deadline-unmeetable"
    assert ei.value.retry_after_ms is None


def test_hint_on_fifo_queue_full_and_timeout():
    """The FIFO (non-QoS) path carries the same hints."""
    mgr = QueryManager(max_concurrent=1, queue_depth=0,
                       admission_timeout_ms=60)
    hog = mgr.admit()
    with pytest.raises(QueryRejectedError) as ei:
        mgr.admit()
    assert ei.value.kind == "queue-full"
    assert ei.value.retry_after_ms is not None
    mgr.finish(hog)
    mgr2 = QueryManager(max_concurrent=1, queue_depth=4,
                        admission_timeout_ms=60)
    hog2 = mgr2.admit()
    with pytest.raises(QueryRejectedError) as ei:
        mgr2.admit()
    assert ei.value.kind == "admission-timeout"
    assert ei.value.retry_after_ms is not None
    mgr2.finish(hog2)


def test_hint_on_dispatch_timeout_backs_off_in_collect_with_retry():
    """Cluster dispatch-timeout rejections (UNAVAILABLE from the
    coordinator barrier) are typed QueryRejectedError subclasses
    carrying retry_after_ms, so collect_with_retry treats a congested
    fleet like any other load rejection: back off and resubmit instead
    of re-raising (ISSUE 20 satellite)."""
    from spark_rapids_tpu.parallel.cluster.coordinator import (
        ClusterDispatchError, dispatch_timeout_error)
    err = dispatch_timeout_error(
        "UNAVAILABLE: cluster dispatch of query 1 incomplete after "
        "50ms (0/4 committed)", queue_depth=4, retry_after_ms=40.0)
    assert isinstance(err, QueryRejectedError)
    assert err.kind == "dispatch-timeout"
    assert err.retry_after_ms == 40.0 and err.queue_depth == 4
    # The message keeps the UNAVAILABLE shape the recovery ladder
    # classifies as transient — subclassing must not change it.
    assert oom.is_transient_error(err)

    calls, sleeps = [], []

    def attempt():
        calls.append(1)
        if len(calls) < 3:
            raise dispatch_timeout_error(
                "UNAVAILABLE: dispatch incomplete", retry_after_ms=40.0)
        return "ok"

    c0 = SC.counters().get("clientRetries", 0)
    assert SC.collect_with_retry(attempt, max_attempts=5,
                                 sleep=sleeps.append) == "ok"
    assert len(calls) == 3
    assert len(sleeps) == 2 and all(s > 0 for s in sleeps)
    assert SC.counters().get("clientRetries", 0) - c0 == 2
    assert SC.counters().get("clientRetries.dispatch-timeout", 0) >= 2

    # Hintless cluster errors (budget exhaustion, poisoned plans) are
    # NOT retryable-by-wait: re-raise immediately, zero sleeps.
    def hopeless():
        raise ClusterDispatchError("stage s3 failed after max retries")

    sleeps2 = []
    with pytest.raises(ClusterDispatchError):
        SC.collect_with_retry(hopeless, max_attempts=5,
                              sleep=sleeps2.append)
    assert sleeps2 == []


# ---------------------------------------------------------------------------
# Resize-at-idle must not drop queued tickets (ISSUE 18 satellite)
# ---------------------------------------------------------------------------

def test_resize_at_idle_redirects_stale_references():
    """A caller holding the OLD manager reference across a conf-change
    resize must land its ticket in the LIVE manager, never a retired
    one — admit/finish/note_pressure all follow the successor chain."""
    from spark_rapids_tpu.api.dataframe import TpuSession

    def conf_for(n):
        s = TpuSession()
        s.set("spark.rapids.sql.scheduler.maxConcurrentQueries", n)
        return s.conf

    with SC._MANAGER_LOCK:
        SC._MANAGER = None
    try:
        old = SC.get_query_manager(conf_for(2))
        # Idle resize retires `old` and installs a successor.
        new = SC.get_query_manager(conf_for(3))
        assert new is not old
        assert old._successor is new
        # A ticket admitted through the STALE reference lands in (and
        # is visible to) the live manager.
        t = old.admit()
        assert new.active_count == 1
        assert old._active == {}
        old.finish(t)
        assert new.active_count == 0
        # The retired manager never resurrects: repeated stale calls
        # keep following the chain even two resizes later.
        newer = SC.get_query_manager(conf_for(4))
        t2 = old.admit()
        assert newer.active_count == 1
        old.finish(t2)
        assert newer.active_count == 0
    finally:
        with SC._MANAGER_LOCK:
            SC._MANAGER = None


def test_resize_skipped_while_active():
    """The flip side: a manager with in-flight work never resizes —
    the bound cannot change under a running query."""
    from spark_rapids_tpu.api.dataframe import TpuSession

    def conf_for(n):
        s = TpuSession()
        s.set("spark.rapids.sql.scheduler.maxConcurrentQueries", n)
        return s.conf

    with SC._MANAGER_LOCK:
        SC._MANAGER = None
    try:
        mgr = SC.get_query_manager(conf_for(2))
        t = mgr.admit()
        same = SC.get_query_manager(conf_for(5))
        assert same is mgr and mgr._successor is None
        mgr.finish(t)
    finally:
        with SC._MANAGER_LOCK:
            SC._MANAGER = None
