"""Class-aware device preemption (ISSUE 18; memory/stores.py classed
gate + plan/planner.py rung 0 + faults.py preempt flag).

The contracts under test:

- The classed gate only ever preempts a STRICTLY lower class: an
  interactive head waiter asks a running background/batch holder to
  yield; equal classes queue without preempting; a holder whose
  per-query preemption budget is spent (``preempt_enabled`` off) is
  never picked as a victim.
- A preempted query yields at a partition boundary, spills its live
  device buffers through the existing ladder, resumes after the
  preemptor drains, and returns rows BYTE-IDENTICAL to a solo run —
  with ``preemptions``/``preemptedMs``/``resumedStages`` recorded and
  an EMPTY leak report.
- Seeded ``oom``/``transient``/``lostoutput`` chaos landing
  mid-preemption-spill / mid-resume (the ``preempt.spill`` /
  ``preempt.resume`` fault sites) re-enters the recovery ladder:
  results stay bit-identical with exactly the expected recovery
  counters.
- With ``scheduler.preemption.enabled=false`` (the default) the gate
  is byte-for-byte the flat class-blind semaphore.
"""

import threading
import time

import pytest

from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.faults import QueryPreemptedError, QueryToken
from spark_rapids_tpu.memory import oom, stores
from spark_rapids_tpu.memory.stores import TpuSemaphore
from spark_rapids_tpu.parallel import scheduler as SC


@pytest.fixture(autouse=True)
def clean_state():
    faults.configure("")
    faults.reset_counters()
    SC.reset_counters()
    oom.reset_degradation()
    # The process-global device semaphore is sized by the FIRST collect
    # in the process (reference semantics); drop it so this module's
    # concurrentTpuTasks=1 actually takes effect — with a wider gate a
    # second query walks straight in and no preemption window exists.
    with stores._GLOBAL_SEM_LOCK:
        stores._GLOBAL_SEM = None
    yield
    faults.configure("")
    faults.reset_counters()
    SC.reset_counters()
    oom.reset_degradation()
    stores._PREEMPT_ENABLED = False
    with stores._GLOBAL_SEM_LOCK:
        stores._GLOBAL_SEM = None


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_preempt"))
    # Enough partitions that a background query reliably has work left
    # when the interactive one arrives at the gate.
    tpch.generate(d, scale=0.02, files_per_table=10, seed=11)
    return d


def _session(preempt=True, tag=None, chaos=""):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    # Cost-based placement would put the tiny final sort on the host,
    # skipping the device collect funnel (and so the gate) entirely.
    s.set("spark.rapids.sql.cost.enabled", False)
    s.set("spark.rapids.sql.scheduler.maxConcurrentQueries", 4)
    s.set("spark.rapids.sql.scheduler.qos.enabled", True)
    s.set("spark.rapids.sql.scheduler.preemption.enabled", preempt)
    s.set("spark.rapids.sql.concurrentTpuTasks", 1)
    s.set("spark.rapids.sql.retry.backoffMs", 1)
    if chaos:
        # Only the chaos session carries the faults key: an explicit
        # empty spec on the OTHER session would disarm the schedule
        # (faults.maybe_configure adopts per collect, last writer wins).
        s.set("spark.rapids.sql.test.faults", chaos)
        s.set("spark.rapids.sql.test.faults.seed", 11)
        s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    if tag is not None:
        s.set("spark.rapids.sql.test.faults.queryTag", tag)
    return s


@pytest.fixture(scope="module")
def baseline(data_dir):
    return tpch.QUERIES["q1"](_session(False), data_dir).collect()


# ---------------------------------------------------------------------------
# Gate unit tests (no data, fabricated tokens)
# ---------------------------------------------------------------------------

def _classed_gate(monkeypatch):
    monkeypatch.setattr(stores, "_PREEMPT_ENABLED", True)
    return TpuSemaphore(1)


def _tok(qid, cls):
    return QueryToken(qid, qos_class=cls)


def test_gate_preempts_lower_class(monkeypatch):
    """An interactive head waiter asks the running background holder to
    yield, naming the preemptor class; the permit hands over once the
    victim releases."""
    sem = _classed_gate(monkeypatch)
    bg = _tok(1, "background")
    sem._acquire_classed(bg)
    assert sem.holders == [(1, 2)]

    it = _tok(2, "interactive")
    got = threading.Event()

    def want():
        sem._acquire_classed(it)
        got.set()

    t = threading.Thread(target=want, daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while not bg.preempt_requested() and time.monotonic() < deadline:
        time.sleep(0.002)
    assert bg.preempt_requested(), "holder never asked to yield"
    assert bg.preemptor_class == "interactive"
    assert sem.preempt_requests == 1
    assert not got.is_set(), "permit handed over before the release"
    sem.release_classed(bg)             # the victim unwinds
    assert got.wait(10)
    sem.release_classed(it)
    t.join(10)


def test_gate_same_class_queues_without_preempting(monkeypatch):
    """Equal classes never preempt each other: the second batch query
    just waits its turn."""
    sem = _classed_gate(monkeypatch)
    a = _tok(1, "batch")
    sem._acquire_classed(a)
    b = _tok(2, "batch")
    got = threading.Event()

    def want():
        sem._acquire_classed(b)
        got.set()

    t = threading.Thread(target=want, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not a.preempt_requested()
    assert sem.preempt_requests == 0
    sem.release_classed(a)
    assert got.wait(10)
    sem.release_classed(b)
    t.join(10)


def test_gate_skips_budget_spent_victims(monkeypatch):
    """A holder whose per-query preemption budget is spent
    (preempt_enabled off) is never picked as a victim."""
    sem = _classed_gate(monkeypatch)
    bg = _tok(1, "background")
    bg.preempt_enabled = False
    sem._acquire_classed(bg)
    it = _tok(2, "interactive")
    got = threading.Event()

    def want():
        sem._acquire_classed(it)
        got.set()

    t = threading.Thread(target=want, daemon=True)
    t.start()
    time.sleep(0.2)
    assert not bg.preempt.is_set()
    assert sem.preempt_requests == 0
    sem.release_classed(bg)
    assert got.wait(10)
    sem.release_classed(it)
    t.join(10)


def test_wait_resume_noop_when_disabled():
    sem = TpuSemaphore(1)
    t0 = time.monotonic()
    sem.wait_resume(_tok(1, "background"))
    assert time.monotonic() - t0 < 0.5


def test_check_preempted_honors_flag_and_budget():
    """faults.check_preempted raises only while the preempt flag is set
    AND the token still honors preemption."""
    tok = _tok(7, "background")
    faults.set_query_token(tok)
    try:
        faults.check_preempted()        # no flag: no-op
        tok.request_preempt("interactive")
        with pytest.raises(QueryPreemptedError) as ei:
            faults.check_preempted()
        assert ei.value.preemptor == "interactive"
        assert ei.value.query_id == 7
        tok.clear_preempt()
        faults.check_preempted()        # cleared: no-op again
        tok.request_preempt("interactive")
        tok.preempt_enabled = False     # budget spent
        faults.check_preempted()
    finally:
        faults.set_query_token(None)


def test_flat_semaphore_unchanged_when_disabled(data_dir, baseline):
    """The default-off gate is byte-for-byte the old flat semaphore:
    background + interactive queries both run, nothing preempts."""
    bg = tpch.QUERIES["q1"](_session(False), data_dir) \
        .submit(priority="background")
    fg = tpch.QUERIES["q1"](_session(False), data_dir) \
        .collect(priority="interactive")
    assert fg == baseline
    assert bg.result(timeout=300) == baseline
    assert SC.counters().get("preemptions", 0) == 0
    assert stores.get_tpu_semaphore(1).holders == []


# ---------------------------------------------------------------------------
# End-to-end preemption (+ chaos riding along)
# ---------------------------------------------------------------------------

def _run_preemption_scenario(data_dir, bg_chaos="", bg_tag=None,
                             attempts=3):
    """Launch a background q1, wait until it holds the device gate, then
    collect an interactive q1 — retrying the whole scenario when timing
    denied a preemption window (the background query drained first).
    Returns (bg_rows, fg_rows, victim_physical)."""
    sem = stores.get_tpu_semaphore(1)
    for attempt in range(attempts):
        SC.reset_counters()
        df_bg = tpch.QUERIES["q1"](
            _session(tag=bg_tag, chaos=bg_chaos), data_dir)
        handle = df_bg.submit(priority="background")
        deadline = time.monotonic() + 60
        while not sem.holders and time.monotonic() < deadline:
            time.sleep(0.001)
        fg = tpch.QUERIES["q1"](_session(), data_dir) \
            .collect(priority="interactive")
        bg = handle.result(timeout=300)
        if SC.counters().get("preemptions", 0) >= 1:
            return bg, fg, df_bg._physical()
    pytest.fail(f"no preemption in {attempts} scenario attempts")


def test_preemption_end_to_end_bit_identical(data_dir, baseline):
    """The victim yields, spills, resumes after the preemptor drains;
    BOTH queries return rows identical to solo runs, the counters
    record the suspension, and the victim's leak report is empty."""
    bg, fg, phys = _run_preemption_scenario(data_dir)
    assert fg == baseline, "preemptor diverged"
    assert bg == baseline, "victim diverged after preemption"
    ctrs = SC.counters()
    assert ctrs.get("preemptions", 0) >= 1
    assert ctrs.get("preemptedMs", 0) > 0
    assert ctrs.get("resumedStages", 0) >= 1, \
        "resume recomputed every stage — durable outputs were dropped"
    assert stores.get_tpu_semaphore(1).preempt_requests >= 1
    ctx = phys.last_ctx
    assert ctx is not None and ctx.last_leak_report == [], \
        f"preempted query leaked buffers: {ctx.last_leak_report}"


@pytest.mark.parametrize("kind,site,counter", [
    # Mid-preemption-spill: the fault fires INSIDE the preemption rung,
    # before the spill moves a byte — it re-enters the ladder as a
    # same-context transient retry.
    ("transient", "preempt.spill", "retriesAttempted"),
    # Mid-resume: the fault fires right after the gate re-granted the
    # victim's class — same ladder, same counters.
    ("transient", "preempt.resume", "retriesAttempted"),
    # A durable output lost mid-resume carries UNAVAILABLE (and no
    # owner at this site), so the whole-query rung recovers it.
    ("lostoutput", "preempt.resume", "retriesAttempted"),
])
def test_preemption_chaos_mid_rung(data_dir, baseline, kind, site,
                                   counter):
    """Seeded faults landing exactly mid-preemption-spill / mid-resume
    stay bit-identical with the expected recovery counters and an empty
    leak report."""
    chaos = f"{kind}@{site}/query=1:1"
    bg, fg, phys = _run_preemption_scenario(
        data_dir, bg_chaos=chaos, bg_tag=1)
    assert fg == baseline
    assert bg == baseline, f"victim diverged under {chaos}"
    assert faults.counters().get(counter, 0) >= 1, \
        f"{chaos} never re-entered the ladder"
    assert faults.counters().get(
        f"faultsInjected.{kind}@{site}", 0) >= 1, \
        f"{chaos} never fired"
    assert SC.counters().get("preemptions", 0) >= 1
    ctx = phys.last_ctx
    assert ctx is not None and ctx.last_leak_report == []


def test_preemption_chaos_oom_in_victim(data_dir, baseline):
    """An injected device OOM in the victim's own dispatch funnel (the
    partitions it runs around the suspension) engages the spill ladder
    as usual: bit-identical rows, the retry recorded, no leaks. (One
    fire only: a second would exhaust the shrink rung into a host
    fallback, which legitimately reorders float sums.)"""
    bg, fg, phys = _run_preemption_scenario(
        data_dir, bg_chaos="oom@upload/query=1:1", bg_tag=1)
    assert fg == baseline
    assert bg == baseline, "victim diverged under injected OOM"
    assert faults.counters().get("retriesAttempted", 0) >= 1
    assert SC.counters().get("preemptions", 0) >= 1
    ctx = phys.last_ctx
    assert ctx is not None and ctx.last_leak_report == []


def test_preemption_budget_caps_yields(data_dir, baseline,
                                       monkeypatch):
    """With maxPerQuery=0 every preemption request is immediately
    declined (budget spent on the first ask): the victim finishes
    without ever yielding again, still bit-identical."""
    sem = stores.get_tpu_semaphore(1)
    s = _session()
    s.set("spark.rapids.sql.scheduler.preemption.maxPerQuery", 0)
    df_bg = tpch.QUERIES["q1"](s, data_dir)
    handle = df_bg.submit(priority="background")
    deadline = time.monotonic() + 60
    while not sem.holders and time.monotonic() < deadline:
        time.sleep(0.001)
    fg = tpch.QUERIES["q1"](_session(), data_dir) \
        .collect(priority="interactive")
    bg = handle.result(timeout=300)
    assert fg == baseline
    assert bg == baseline
    # The gate may have asked, but the rung never paid a suspension.
    assert SC.counters().get("preemptions", 0) == 0
