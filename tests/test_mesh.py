"""Multi-chip collective tests on the 8-virtual-device CPU mesh
(conftest.py forces xla_force_host_platform_device_count=8 — SURVEY.md §4's
"distributed without a cluster" strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch, host_to_device, \
    device_to_host
from spark_rapids_tpu.exprs.base import BoundReference as Ref
from spark_rapids_tpu.ops import AggSpec, CountStar, HashAggregateExec, Sum
from spark_rapids_tpu.parallel import HashPartitioning
from spark_rapids_tpu.parallel import mesh as M


N_DEV = 8


def make_shards(rng, rows_per_dev=64, n_dev=N_DEV):
    shards = []
    all_rows = []
    for d in range(n_dev):
        keys = rng.integers(0, 37, rows_per_dev).tolist()
        vals = rng.integers(0, 1000, rows_per_dev).tolist()
        all_rows.extend(zip(keys, vals))
        hb = HostBatch.from_pydict(
            [("k", dt.INT64), ("v", dt.INT64)],
            {"k": keys, "v": vals})
        shards.append(host_to_device(hb, capacity=rows_per_dev))
    return shards, all_rows


def test_distributed_aggregate_step(rng):
    assert len(jax.devices()) >= N_DEV
    mesh = M.make_mesh(N_DEV)
    shards, all_rows = make_shards(rng)
    agg = HashAggregateExec.__new__(HashAggregateExec)
    # Build the exec without a child: only its kernels are used.
    HashAggregateExec.__init__(
        agg, _DummyChild(), [("k", Ref(0, dt.INT64))],
        [AggSpec("s", Sum(Ref(1, dt.INT64))),
         AggSpec("n", CountStar(None))])
    part = HashPartitioning([Ref(0, dt.INT64)], N_DEV)
    step = M.distributed_aggregate_step(mesh, agg, part)
    global_batch = M.shard_batches(mesh, shards)
    out = step(global_batch)
    # Collect per-device results and compare against a python oracle.
    got = {}
    for d in range(N_DEV):
        local = jax.tree.map(lambda x: np.asarray(x)[d], out)
        from spark_rapids_tpu.columnar.batch import DeviceBatch
        hb = device_to_host(local)
        for k, s, n in hb.to_pylist():
            assert k not in got, f"group {k} on two devices"
            got[k] = (s, n)
    expected = {}
    for k, v in all_rows:
        s, n = expected.get(k, (0, 0))
        expected[k] = (s + v, n + 1)
    assert got == expected


def test_all_gather_batch(rng):
    mesh = M.make_mesh(N_DEV)
    shards, all_rows = make_shards(rng, rows_per_dev=16)
    global_batch = M.shard_batches(mesh, shards)

    from jax.sharding import PartitionSpec as P
    from spark_rapids_tpu.shims import shard_map

    def inner(stacked):
        local = jax.tree.map(lambda x: x[0], stacked)
        full = M.all_gather_batch(local, N_DEV)
        return jax.tree.map(lambda x: x[None], full)

    fn = jax.jit(shard_map(inner, mesh, in_specs=(P("data"),),
                           out_specs=P("data")))
    out = fn(global_batch)
    # Every device should now hold all rows.
    for d in range(N_DEV):
        local = jax.tree.map(lambda x: np.asarray(x)[d], out)
        hb = device_to_host(local)
        assert sorted(hb.to_pylist()) == sorted(all_rows)


class _DummyChild:
    """Placeholder child for kernel-only HashAggregateExec use."""

    schema = ()
    children = ()

    def num_partitions(self, ctx):
        return 1
