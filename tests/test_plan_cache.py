"""Parameterized plan cache (ISSUE 10): zero re-plan, zero re-trace
repeated-query serving.

Parity contract: cached-vs-fresh execution is BIT-IDENTICAL across the
11-query bench suite, including rebinding with different literals, with
``planCache.enabled=false`` as the control and armed chaos schedules
proving the bypass. Mechanism contracts: a rebind of the same shape is
a plan-cache hit with ZERO kernel-cache misses (literals travel as
traced runtime inputs, satellite #1), pushed-down scan predicates
resolve against the EXECUTION's binding (row-group skipping can never
reuse the template's first literals), invalidation covers conf and
schema changes, and explain/explain_analyze annotate provenance
(satellite #2).
"""

import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.ops import kernel_cache as kc
from spark_rapids_tpu.plan import plan_cache as pc
from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col


def _session(plan_cache=True, chaos="", **extra):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.planCache.enabled", plan_cache)
    if chaos:
        s.set("spark.rapids.sql.test.faults", chaos)
        s.set("spark.rapids.sql.test.faults.seed", 7)
    for k, v in extra.items():
        s.set(k, v)
    return s


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    from spark_rapids_tpu.benchmarks import tpch
    d = str(tmp_path_factory.mktemp("plan_cache_tpch"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


@pytest.fixture(scope="module")
def suites_dir(tmp_path_factory):
    from spark_rapids_tpu.benchmarks import suites
    d = str(tmp_path_factory.mktemp("plan_cache_suites"))
    suites.generate(d, scale=0.01, files_per_table=2)
    return d


def _q6(session, data_dir, lo="1994-01-01", hi="1995-01-01"):
    """Parameterized q6: the date range is the binding."""
    from spark_rapids_tpu.benchmarks import tpch
    li = tpch._read(session, data_dir, "lineitem")
    f = li.filter(
        (col("l_shipdate") >= lit_col(tpch.days(lo)))
        & (col("l_shipdate") < lit_col(tpch.days(hi)))
        & (col("l_discount") >= 0.05) & (col("l_discount") <= 0.07)
        & (col("l_quantity") < 24.0))
    return f.agg(agg_sum(col("l_extendedprice") * col("l_discount"))
                 .alias("revenue"))


# ---------------------------------------------------------------------------
# The serving fast path: hit + bind-only + zero retrace
# ---------------------------------------------------------------------------

def test_rebind_hits_with_zero_kernel_misses(tpch_dir):
    """Satellite #1 acceptance: two different literal bindings of the
    same shape share ONE template and ONE set of compiled kernels —
    the second collect re-traces NOTHING."""
    s = _session()
    _q6(s, tpch_dir).collect()                      # template + compile
    st0 = pc.cache().stats()
    k0 = kc.cache().stats()
    got = _q6(s, tpch_dir, "1995-01-01", "1996-01-01").collect()
    st1 = pc.cache().stats()
    k1 = kc.cache().stats()
    assert st1["hits"] == st0["hits"] + 1, (st0, st1)
    assert k1["misses"] == k0["misses"], \
        f"rebinding re-traced kernels: {k0} -> {k1}"
    # Bit-identical to a fresh, cache-off plan of the same binding.
    control = _q6(_session(plan_cache=False), tpch_dir,
                  "1995-01-01", "1996-01-01").collect()
    assert got == control


def test_same_literals_rebuild_is_a_hit(tpch_dir):
    from spark_rapids_tpu.benchmarks import tpch
    s = _session()
    a = tpch.QUERIES["q1"](s, tpch_dir).collect()
    st0 = pc.cache().stats()
    b = tpch.QUERIES["q1"](s, tpch_dir).collect()
    st1 = pc.cache().stats()
    assert st1["hits"] == st0["hits"] + 1
    assert a == b


def test_limit_values_bind(tpch_dir):
    from spark_rapids_tpu.benchmarks import tpch
    s = _session()
    li = tpch._read(s, tpch_dir, "lineitem")
    base = li.select("l_orderkey", "l_quantity")
    a = base.limit(3).collect()
    st0 = pc.cache().stats()
    b = base.limit(9).collect()
    st1 = pc.cache().stats()
    assert len(a) == 3 and len(b) == 9
    assert st1["hits"] == st0["hits"] + 1, (st0, st1)


def test_pushdown_predicates_resolve_per_binding(tmp_path):
    """THE row-group pruning trap: a template cached with binding A's
    pushed predicates must skip row groups according to binding B's
    literals on the rebound run — never A's."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as papq
    path = str(tmp_path / "t.parquet")
    tab = pa.table({"x": pa.array(np.arange(400, dtype=np.int64)),
                    "y": pa.array(np.arange(400.0))})
    papq.write_table(tab, path, row_group_size=100)
    s = _session()
    base = s.read.parquet(path)

    def q(lo, hi):
        return base.filter((col("x") >= lit_col(lo))
                           & (col("x") < lit_col(hi)))

    a = q(10, 20).collect()
    assert [r[0] for r in a] == list(range(10, 20))
    st0 = pc.cache().stats()
    # Binding B lives entirely in the LAST row group: a stale-predicate
    # skip would return zero rows.
    df = q(350, 360)
    b = df.collect()
    assert pc.cache().stats()["hits"] == st0["hits"] + 1
    assert [r[0] for r in b] == list(range(350, 360))
    skipped = sum(v.get("numSkippedRowGroups", 0)
                  for v in df.metrics().values())
    assert skipped >= 3, "stats skipping stopped working under binding"


# ---------------------------------------------------------------------------
# Invalidation & bypass
# ---------------------------------------------------------------------------

def test_conf_change_invalidates(tpch_dir):
    s = _session()
    _q6(s, tpch_dir).collect()
    st0 = pc.cache().stats()
    s.set("spark.rapids.sql.shuffle.partitions", 3)
    _q6(s, tpch_dir).collect()
    st1 = pc.cache().stats()
    assert st1["misses"] == st0["misses"] + 1, (st0, st1)


def test_schema_change_misses():
    s = _session()
    data = {"a": [1, 2, 3]}
    d32 = s.create_dataframe(data, [("a", dt.INT32)])
    d64 = s.create_dataframe(data, [("a", dt.INT64)])
    r32 = d32.filter(col("a") > lit_col(1)).collect()
    st0 = pc.cache().stats()
    r64 = d64.filter(col("a") > lit_col(1)).collect()
    st1 = pc.cache().stats()
    assert st1["misses"] == st0["misses"] + 1
    assert r32 == r64 == [(2,), (3,)]


def test_armed_faults_bypass_and_stay_bit_identical(tpch_dir):
    from spark_rapids_tpu import faults
    want = _q6(_session(), tpch_dir).collect()
    c0 = pc.counters().get("planCacheBypasses", 0)
    chaos = "oom@upload:1,oom@kernel:1,transient@download:1"
    got = _q6(_session(chaos=chaos), tpch_dir).collect()
    c1 = pc.counters().get("planCacheBypasses", 0)
    assert c1 == c0 + 1, "armed fault schedule must bypass the cache"
    assert got == want
    assert faults.counters().get("faultsInjected", 0) > 0


def test_disabled_control_returns_plain_physical_plan(tpch_dir):
    df = _q6(_session(plan_cache=False), tpch_dir)
    phys = df._physical()
    assert not hasattr(phys, "provenance")
    assert "plan-cache" not in df.explain("ALL")


# ---------------------------------------------------------------------------
# Provenance & handles (satellite #2)
# ---------------------------------------------------------------------------

def test_explain_annotates_provenance(tpch_dir):
    pc.cache().clear()      # earlier tests cached this shape
    s = _session()
    first = _q6(s, tpch_dir)
    rep0 = first.explain("ALL")
    assert "plan-cache miss, template planned" in rep0
    rebound = _q6(s, tpch_dir, "1995-01-01", "1996-01-01")
    rep1 = rebound.explain("ALL")
    assert "plan-cache hit, bind-only" in rep1


def test_explain_analyze_annotates_provenance(tpch_dir):
    s = _session()
    _q6(s, tpch_dir).collect()
    rebound = _q6(s, tpch_dir, "1993-01-01", "1994-01-01")
    report = rebound.explain_analyze()
    assert "plan-cache hit, bind-only" in report


def test_prepare_returns_bound_handle(tpch_dir):
    s = _session()
    _q6(s, tpch_dir).collect()
    handle = _q6(s, tpch_dir, "1995-01-01", "1996-01-01").prepare()
    assert handle.cache_hit
    assert len(handle.bind_values) >= 2
    rows = handle.collect()
    control = _q6(_session(plan_cache=False), tpch_dir,
                  "1995-01-01", "1996-01-01").collect()
    assert rows == control


def test_scheduler_per_tenant_stats(tpch_dir):
    s = _session()
    _q6(s, tpch_dir).collect()
    df = _q6(s, tpch_dir, "1996-01-01", "1997-01-01")
    df.collect()
    sched = df.metrics().get("Scheduler@query", {})
    assert sched.get("planCacheBindOnly") == 1, sched


def test_plan_bind_span_under_budget(tpch_dir):
    """Acceptance: steady-state plan+bind < 5ms, measured via the trace
    span (generous 50ms CI bound; bench.py reports the real number)."""
    from spark_rapids_tpu import monitoring
    s = _session()
    s.set("spark.rapids.sql.trace.enabled", True)
    _q6(s, tpch_dir).collect()
    monitoring.reset()
    _q6(s, tpch_dir, "1995-06-01", "1995-12-01").collect()
    spans = [e for events in
             (monitoring.events(q) for q in monitoring.query_ids())
             for e in events if e[1] == "plan-bind"]
    assert spans, "plan-bind span missing"
    dur_ms = spans[-1][4] / 1e6
    args = spans[-1][7]
    assert args and args.get("planCacheHit") is True, args
    assert dur_ms < 50.0, f"plan+bind took {dur_ms:.1f}ms"
    monitoring.configure(False)
    monitoring.reset()


# ---------------------------------------------------------------------------
# Parity suite: 11 bench queries cached-vs-fresh, rebind, chaos control
# ---------------------------------------------------------------------------

# Fast tier runs q6 only (the serving shape the mechanism tests above
# already exercise end to end); the CI plan-cache chaos entry runs the
# full 11-query sweep without the slow filter.
_TPCH = ["q6",
         pytest.param("q1", marks=pytest.mark.slow),
         pytest.param("q3", marks=pytest.mark.slow),
         pytest.param("q5", marks=pytest.mark.slow),
         pytest.param("q12", marks=pytest.mark.slow),
         pytest.param("q14", marks=pytest.mark.slow)]
_SUITES = [pytest.param("repart", marks=pytest.mark.slow),
           pytest.param("q67", marks=pytest.mark.slow),
           pytest.param("xbb_q5", marks=pytest.mark.slow),
           pytest.param("ds_q3", marks=pytest.mark.slow),
           pytest.param("xbb_q12", marks=pytest.mark.slow)]

_CHAOS = "oom@kernel:1,transient@exchange.flush:1"


def _parity_check(mod, qname, ddir):
    """cached (miss) == cached (rebind hit) == cache-off control ==
    chaos-bypass run, bit for bit."""
    fresh = mod.QUERIES[qname](_session(plan_cache=False), ddir).collect()
    cached = mod.QUERIES[qname](_session(), ddir).collect()
    st0 = pc.cache().stats()
    rebound = mod.QUERIES[qname](_session(), ddir).collect()
    assert pc.cache().stats()["hits"] > st0["hits"]
    assert cached == fresh
    assert rebound == fresh
    chaos = mod.QUERIES[qname](_session(chaos=_CHAOS), ddir).collect()
    assert chaos == fresh


@pytest.mark.parametrize("qname", _TPCH)
def test_parity_tpch(qname, tpch_dir):
    from spark_rapids_tpu.benchmarks import tpch
    _parity_check(tpch, qname, tpch_dir)


@pytest.mark.parametrize("qname", _SUITES)
def test_parity_suites(qname, suites_dir):
    from spark_rapids_tpu.benchmarks import suites
    _parity_check(suites, qname, suites_dir)


def test_parity_two_bindings_q6(tpch_dir):
    """Two genuinely different literal bindings, each checked against
    its own cache-off control."""
    s = _session()
    for lo, hi in (("1994-01-01", "1995-01-01"),
                   ("1995-01-01", "1996-01-01")):
        got = _q6(s, tpch_dir, lo, hi).collect()
        want = _q6(_session(plan_cache=False), tpch_dir, lo, hi).collect()
        assert got == want, (lo, hi)


@pytest.mark.slow
def test_parity_two_bindings_q1(tpch_dir):
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.plan.logical import agg_avg, agg_count

    def q1(session, cutoff):
        li = tpch._read(session, tpch_dir, "lineitem")
        disc = li.filter(col("l_shipdate") <= lit_col(tpch.days(cutoff))) \
            .with_column("disc_price",
                         col("l_extendedprice") * (1.0 - col("l_discount")))
        return disc.group_by("l_returnflag", "l_linestatus").agg(
            agg_sum(col("disc_price")).alias("sum_disc_price"),
            agg_avg(col("l_quantity")).alias("avg_qty"),
            agg_count().alias("n"),
        ).order_by("l_returnflag", "l_linestatus")

    s = _session()
    for cutoff in ("1998-09-02", "1995-06-17"):
        got = q1(s, cutoff).collect()
        want = q1(_session(plan_cache=False), cutoff).collect()
        assert got == want, cutoff


# ---------------------------------------------------------------------------
# Unit: parameterization rules
# ---------------------------------------------------------------------------

def test_parameterize_hoists_only_safe_positions():
    from spark_rapids_tpu.plan import logical as L
    s = TpuSession()
    df = s.create_dataframe({"a": [1], "s": ["xy"]},
                            [("a", dt.INT64), ("s", dt.STRING)])
    shaped = df.filter((col("a") > lit_col(5))
                       & (col("s") == lit_col("xy"))
                       & col("s").isin("p", "q"))
    param, values, dtypes = pc.parameterize(shaped._plan)
    # The int comparison hoists; the string literal and the isin set are
    # structural (width buckets / set membership) and stay inline.
    assert values == (5,)
    assert dtypes == (dt.INT32,)


def test_parameterize_slot_order_deterministic():
    s = TpuSession()
    df = s.create_dataframe({"a": [1]}, [("a", dt.INT64)])
    shaped = df.filter(col("a") > lit_col(3)) \
        .with_column("b", col("a") * 2) \
        .limit(4)
    _, v1, t1 = pc.parameterize(shaped._plan)
    _, v2, t2 = pc.parameterize(shaped._plan)
    assert v1 == v2 == (3, 2, 4)
    assert t1 == t2


def test_uncacheable_shapes_plan_fresh():
    """Opaque shapes (pandas UDF nodes) bypass rather than mis-key."""
    s = _session()
    df = s.create_dataframe({"a": [1, 2]}, [("a", dt.INT64)])
    out = df.map_in_pandas(lambda it: it, [("a", dt.INT64)])
    c0 = pc.counters().get("planCacheUncacheable", 0)
    rows = out.collect()
    assert sorted(rows) == [(1,), (2,)]
    assert pc.counters().get("planCacheUncacheable", 0) == c0 + 1


def test_int64_literal_gets_wide_slot():
    s = _session()
    df = s.create_dataframe({"a": [2**40, 5]}, [("a", dt.INT64)])
    got = df.filter(col("a") > lit_col(2**35)).collect()
    assert got == [(2**40,)]
    _, values, dtypes = pc.parameterize(
        df.filter(col("a") > lit_col(2**35))._plan)
    assert dtypes == (dt.INT64,)
