"""Lineage-scoped recovery suite (ISSUE 3): stage DAG + durable stage
outputs, the execution watchdog, and mesh degrade.

The contract under test, scoped smallest first:

- a ``stall`` is killed by the watchdog and the PARTITION retry succeeds
  within ``watchdog.maxAttempts``;
- a ``lostoutput`` on a reduce-side read recomputes ONLY the owning
  stage (``stageRecomputes == 1``; sibling stages' scans never re-run),
  with results bit-identical to the fault-free run;
- a failed mesh collective demotes that query's exchanges to the
  single-process shuffle path (``meshDegrades``) instead of dying;
- a repeated collect() after a fault-recovered collect is bit-identical
  and does NOT re-fire already-consumed count faults.

The CI chaos matrix runs this file (including the slow-marked TPC-H
q3/q6 runs under the watchdog) with a fixed seed.
"""

import pytest

from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu.ops.base import ExecContext, InMemorySourceExec
from spark_rapids_tpu.parallel import stages as S
from spark_rapids_tpu.plan.logical import agg_sum, col


@pytest.fixture(autouse=True)
def clean_fault_state():
    """Explicitly disarm around every test (the conftest snapshot
    fixture restores prior state; this pins a known-clean start)."""
    faults.configure("")
    faults.reset_counters()
    yield


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_stagerec"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


def _session(chaos: str = "") -> TpuSession:
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.test.faults", chaos)
    s.set("spark.rapids.sql.test.faults.seed", 7)
    s.set("spark.rapids.sql.retry.backoffMs", 1)
    # Scan counters must reflect real (re-)execution, and shuffle joins
    # give q3 its 2-exchange reduce-side shape.
    s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
    return s


def _scan_batch_counts(df):
    """numOutputBatches per FileScanExec instance of the LAST collect,
    ordered stably by the scan's first file path (the per-table identity
    two different plans of the same query share)."""
    from spark_rapids_tpu.io.scan import FileScanExec
    phys = df._physical()
    ctx = phys.last_ctx
    out = {}

    def walk(op):
        if isinstance(op, FileScanExec):
            m = ctx.metrics.get(f"{op.name}@{id(op):x}")
            out[min(op.paths)] = \
                (m.values.get("numOutputBatches", 0) if m else 0)
        for c in op.children:
            walk(c)

    walk(phys.root)
    return out


# ---------------------------------------------------------------------------
# Stage DAG structure
# ---------------------------------------------------------------------------

class TestStageGraph:
    def _join_df(self, s):
        left = s.create_dataframe(
            {"k": [1, 2, 3, 4], "v": [10, 20, 30, 40]},
            [("k", dt.INT64), ("v", dt.INT64)])
        right = s.create_dataframe(
            {"k": [2, 3, 4, 5], "w": [200, 300, 400, 500]},
            [("k", dt.INT64), ("w", dt.INT64)])
        return left.join_on(right, ["k"], ["k"], strategy="shuffle")

    def test_two_exchange_join_builds_three_stages(self):
        phys = self._join_df(_session())._physical()
        g = S.build_stage_graph(phys.root)
        assert len(g) == 3
        result = g.stages[g.root_stage_id]
        assert result.boundary is None
        assert sorted(result.parents) == sorted(
            sid for sid in g.stages if sid != g.root_stage_id)
        for sid in result.parents:
            st = g.stages[sid]
            assert S.is_stage_boundary(st.boundary)
            assert g.stage_of_exchange(id(st.boundary)) is st

    def test_q3_stage_lineage(self, data_dir):
        phys = tpch.QUERIES["q3"](_session(), data_dir)._physical()
        g = S.build_stage_graph(phys.root)
        # Shuffle-forced q3: join exchanges x4, aggregate exchange,
        # range-sort exchange, global-limit single exchange + the
        # result stage.
        assert len(g) >= 6
        # Every exchange is resolvable back to exactly one stage.
        boundaries = [st.boundary for st in g.stages.values()
                      if st.boundary is not None]
        assert len({id(b) for b in boundaries}) == len(boundaries)
        # Lineage is a DAG rooted at the result stage: every non-result
        # stage is some stage's parent.
        children = {sid for st in g.stages.values() for sid in st.parents}
        assert children == set(g.stages) - {g.root_stage_id}

    def test_stage_invalidate_closes_buckets_and_recomputes(self):
        df = _session().create_dataframe(
            {"a": list(range(16))}, [("a", dt.INT64)],
            num_partitions=2).repartition(4, "a")
        phys = df._physical()
        g = S.build_stage_graph(phys.root)
        assert len(g) == 2
        ctx = ExecContext(phys.conf)
        rows1 = phys.root.collect(ctx, device=True)
        assert ctx.catalog.registered_count > 0
        (ex_stage,) = [st for st in g.stages.values()
                       if st.boundary is not None]
        S.invalidate_stage(ctx, ex_stage)
        assert ctx.catalog.registered_count == 0
        rows2 = phys.root.collect(ctx, device=True)
        assert sorted(rows2) == sorted(rows1)
        ctx.close()


# ---------------------------------------------------------------------------
# lostoutput: recompute only the owning stage (the acceptance scenario)
# ---------------------------------------------------------------------------

class TestLostOutputRecovery:
    def test_q3_lostoutput_recomputes_only_lost_stage(self, data_dir):
        free_df = tpch.QUERIES["q3"](_session(), data_dir)
        free = free_df.collect()
        free_scans = _scan_batch_counts(free_df)

        df = tpch.QUERIES["q3"](
            _session("lostoutput@exchange.serve:1"), data_dir)
        got = df.collect()
        # Bit-identical to the fault-free run.
        assert got == free
        rec = df.metrics()["Recovery@query"]
        assert rec.get("stageRecomputes") == 1, rec
        assert faults.counters().get("stageRecomputes") == 1
        # Only the lost stage's scan re-executed: exactly one scan's
        # batch counter doubled, the sibling stages' scans are untouched.
        fault_scans = _scan_batch_counts(df)
        assert set(fault_scans) == set(free_scans)
        doubled = [p for p in free_scans
                   if fault_scans[p] == 2 * free_scans[p]
                   and free_scans[p] > 0]
        untouched = [p for p in free_scans
                     if fault_scans[p] == free_scans[p]]
        assert len(doubled) == 1 and \
            len(untouched) == len(free_scans) - 1, \
            (free_scans, fault_scans)

    def test_lostoutput_checksum_path_counts_in_metrics(self):
        # Inline 2-stage aggregate: lostoutput on the reduce-side read of
        # the partial->final exchange recomputes the partial stage only.
        s = _session("lostoutput@exchange.serve:1")
        df = s.create_dataframe(
            {"k": [i % 3 for i in range(24)], "v": list(range(24))},
            [("k", dt.INT64), ("v", dt.INT64)],
            num_partitions=2).group_by("k").agg(
                agg_sum(col("v")).alias("s"))
        want = sorted(s.create_dataframe(
            {"k": [i % 3 for i in range(24)], "v": list(range(24))},
            [("k", dt.INT64), ("v", dt.INT64)]).group_by("k").agg(
                agg_sum(col("v")).alias("s")).collect_host())
        assert sorted(df.collect()) == want
        rec = df.metrics()["Recovery@query"]
        assert rec.get("stageRecomputes") == 1, rec

    def test_lostoutput_falls_back_to_whole_query_when_disabled(self):
        s = _session("lostoutput@exchange.serve:1")
        s.set("spark.rapids.sql.recovery.stageRecompute.enabled", False)
        df = s.create_dataframe(
            {"k": [1, 1, 2], "v": [1, 2, 3]},
            [("k", dt.INT64), ("v", dt.INT64)]).group_by("k").agg(
                agg_sum(col("v")).alias("s"))
        assert sorted(df.collect()) == [(1, 3), (2, 3)]
        c = faults.counters()
        # The loss carries the UNAVAILABLE marker, so the whole-query
        # retry recovered it — no stage recompute happened.
        assert c.get("stageRecomputes", 0) == 0
        assert c.get("retriesAttempted", 0) >= 1

    def test_repeated_collect_after_recovery_no_refire(self):
        """Regression (ISSUE 3 satellite): a second collect on the same
        DataFrame after a fault-recovered first collect is bit-identical
        and does not re-fire already-consumed count faults."""
        s = _session("lostoutput@exchange.serve:1")
        df = s.create_dataframe(
            {"k": [i % 4 for i in range(32)], "v": list(range(32))},
            [("k", dt.INT64), ("v", dt.INT64)],
            num_partitions=2).group_by("k").agg(
                agg_sum(col("v")).alias("s"))
        r1 = sorted(df.collect())
        assert faults.counters().get("stageRecomputes") == 1
        assert faults.counters().get("faultsInjected") == 1
        r2 = sorted(df.collect())
        assert r2 == r1
        # The consumed schedule stayed consumed: no new injection, no
        # new recompute, and the second run's metrics are clean.
        assert faults.counters().get("faultsInjected") == 1
        assert faults.counters().get("stageRecomputes") == 1
        rec2 = df.metrics().get("Recovery@query", {})
        assert rec2.get("stageRecomputes", 0) == 0, rec2

    def test_repeated_collect_after_transient_recovery(self):
        s = _session("transient@download:1")
        df = s.create_dataframe({"a": [1, 2, 3]}, [("a", dt.INT64)])
        r1 = sorted(df.collect())
        assert r1 == [(1,), (2,), (3,)]
        assert faults.counters().get("faultsInjected") == 1
        assert sorted(df.collect()) == r1
        assert faults.counters().get("faultsInjected") == 1


# ---------------------------------------------------------------------------
# Execution watchdog: stalls killed, partitions re-dispatched
# ---------------------------------------------------------------------------

class TestWatchdog:
    def _wd_session(self, chaos, timeout_ms=1500, attempts=2):
        s = _session(chaos)
        s.set("spark.rapids.sql.watchdog.enabled", True)
        s.set("spark.rapids.sql.watchdog.taskTimeoutMs", timeout_ms)
        s.set("spark.rapids.sql.watchdog.maxAttempts", attempts)
        return s

    def test_stall_killed_and_partition_retry_succeeds(self):
        s = self._wd_session("stall@upload:1")
        df = s.create_dataframe({"a": [1, 2, 3]}, [("a", dt.INT64)])
        assert sorted(df.collect()) == [(1,), (2,), (3,)]
        c = faults.counters()
        assert c.get("watchdogKills", 0) >= 1, c
        assert c.get("partitionRetries", 0) >= 1, c
        rec = df.metrics()["Recovery@query"]
        assert rec.get("watchdogKills", 0) >= 1, rec

    def test_watchdog_exhausted_demotes_to_query_retry(self):
        # Both watchdog attempts stall -> DEADLINE_EXCEEDED -> the
        # transient rung re-runs the query; the consumed schedule lets
        # the third execution through (demotion order end-to-end).
        s = self._wd_session("stall@upload:2", timeout_ms=800)
        df = s.create_dataframe({"a": [7, 8]}, [("a", dt.INT64)])
        assert sorted(df.collect()) == [(7,), (8,)]
        c = faults.counters()
        assert c.get("watchdogKills", 0) >= 2, c
        assert c.get("retriesAttempted", 0) >= 1, c

    def test_stall_without_watchdog_is_bounded(self, monkeypatch):
        # Safety net: with no watchdog armed a stall unwinds as
        # DEADLINE_EXCEEDED after the bounded nap and the transient
        # retry recovers the query.
        monkeypatch.setattr(faults, "STALL_TIMEOUT_S", 0.05)
        s = _session("stall@upload:1")
        df = s.create_dataframe({"a": [5]}, [("a", dt.INT64)])
        assert df.collect() == [(5,)]
        assert faults.counters().get("retriesAttempted", 0) >= 1

    @pytest.mark.slow
    @pytest.mark.parametrize("qname", ["q6", "q3"])
    def test_tpch_under_watchdog_stall_lostoutput(self, qname, data_dir):
        """The CI chaos-matrix entry: TPC-H under the watchdog with a
        stall + lostoutput schedule, bit-identical to fault-free."""
        free = tpch.QUERIES[qname](_session(), data_dir).collect()
        s = self._wd_session(
            "stall@upload:1,lostoutput@exchange.serve:1",
            timeout_ms=20000, attempts=2)
        df = tpch.QUERIES[qname](s, data_dir)
        assert df.collect() == free
        c = faults.counters()
        assert c.get("faultsInjected", 0) >= 2, c
        assert c.get("watchdogKills", 0) >= 1, c
        assert c.get("stageRecomputes", 0) >= 1, c


# ---------------------------------------------------------------------------
# Mesh degrade: collective failure demotes to the single-process path
# ---------------------------------------------------------------------------

class TestMeshDegrade:
    def _df(self, s):
        return s.create_dataframe(
            {"k": [i % 5 for i in range(40)], "v": list(range(40))},
            [("k", dt.INT64), ("v", dt.INT64)],
            num_partitions=4).group_by("k").agg(
                agg_sum(col("v")).alias("s"))

    def test_mesh_collective_failure_degrades_not_dies(self):
        want = sorted(self._df(_session()).collect())
        s = _session("transient@mesh.exchange:1")
        s.set("spark.rapids.sql.mesh.enabled", True)
        df = self._df(s)
        assert sorted(df.collect()) == want
        c = faults.counters()
        assert c.get("meshDegrades", 0) >= 1, c
        rec = df.metrics()["Recovery@query"]
        assert rec.get("meshDegrades", 0) >= 1, rec

    def test_mesh_degrade_disabled_propagates_to_query_retry(self):
        s = _session("transient@mesh.exchange:1")
        s.set("spark.rapids.sql.mesh.enabled", True)
        s.set("spark.rapids.sql.mesh.degrade.enabled", False)
        df = self._df(s)
        want = sorted(self._df(_session()).collect())
        assert sorted(df.collect()) == want
        c = faults.counters()
        assert c.get("meshDegrades", 0) == 0, c
        assert c.get("retriesAttempted", 0) >= 1, c


# ---------------------------------------------------------------------------
# Durable broadcast outputs (satellite: free the device copy on degrade)
# ---------------------------------------------------------------------------

class TestBroadcastDurableOutput:
    def _bx(self):
        from spark_rapids_tpu.parallel.exchange import BroadcastExchangeExec
        schema = (("a", dt.INT64),)
        hb = HostBatch.from_pydict(schema, {"a": [1, 2, 3]})
        return BroadcastExchangeExec(InMemorySourceExec(schema, [[hb]]))

    def test_device_single_is_catalog_registered(self):
        bx = self._bx()
        ctx = ExecContext()
        b = bx.collect_single_device(ctx)
        assert int(b.live_count()) == 3
        assert ctx.catalog.registered_count == 1
        # Re-serving acquires the SAME durable output, not a rebuild.
        b2 = bx.collect_single_device(ctx)
        assert ctx.catalog.registered_count == 1
        assert int(b2.live_count()) == 3
        ctx.close()

    def test_host_fallback_frees_device_copy(self):
        bx = self._bx()
        ctx = ExecContext()
        bx.collect_single_device(ctx)
        assert ctx.catalog.registered_count == 1
        merged = bx.collect_single_host(ctx)
        assert merged.num_rows == 3
        # Host degrade of the consuming subtree: the device single is
        # freed instead of pinning both copies for the query's lifetime.
        assert bx._cache_key(True) not in ctx.cache
        assert ctx.catalog.registered_count == 0
        # A later device consumer transparently rebuilds.
        bx.collect_single_device(ctx)
        assert ctx.catalog.registered_count == 1
        ctx.close()

    def test_stage_invalidate_drops_both_copies(self):
        bx = self._bx()
        ctx = ExecContext()
        bx.collect_single_device(ctx)
        bx.stage_invalidate(ctx)
        assert ctx.catalog.registered_count == 0
        assert bx._cache_key(True) not in ctx.cache
        ctx.close()


# ---------------------------------------------------------------------------
# Fault-registry hygiene (satellite: snapshot/restore isolation)
# ---------------------------------------------------------------------------

class TestRegistryIsolation:
    def test_snapshot_restore_roundtrip(self):
        state = faults.snapshot()
        faults.configure("oom@somewhere:3", seed=11)
        faults.record("somethingOdd", 2)
        assert faults.injector() is not None
        faults.restore(state)
        assert faults.injector() is None          # clean_fault_state disarmed
        assert "somethingOdd" not in faults.counters()

    def test_armed_schedule_does_not_leak(self):
        # Arm without cleaning up: the conftest autouse fixture must
        # restore a clean registry before the NEXT test runs. Paired
        # with test_snapshot_restore_roundtrip's disarmed assertion,
        # any leak across tests in this class would trip there.
        faults.configure("transient@nowhere:5", seed=3)
        assert faults.injector() is not None
