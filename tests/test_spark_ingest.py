"""Plugin-mode slice (VERDICT r4 item 9): ingest CAPTURED Spark physical
plans — the text a user's real cluster prints from df.explain() — and
execute them on this engine with results matching the pandas oracle
(SQLPlugin.scala:28 / GpuOverrides.scala:1991 identity, via plan capture
instead of an in-JVM hook)."""

import os

import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.plan.spark_ingest import (
    SparkPlanParseError, ingest_spark_plan)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures",
                        "spark_plans")


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpch_ingest")
    tpch.generate(str(d), scale=0.01, files_per_table=2)
    return str(d)


def _tables(data_dir):
    return {t: tpch._paths(data_dir, t)
            for t in ("lineitem", "orders", "customer")}


def _session():
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.hasNans", False)
    # These tests assert the ingested plan lands ON the device; the cost
    # model would (correctly) host-place the mini-scale fixtures.
    s.set("spark.rapids.sql.cost.enabled", False)
    return s


@pytest.mark.parametrize("qn", ["q6", "q3"])
def test_captured_plan_matches_pandas(qn, data_dir):
    text = open(os.path.join(FIXTURES, f"{qn}.txt")).read()
    df = ingest_spark_plan(text, _session(), _tables(data_dir))
    got = df.collect()
    want = tpch.pandas_query(qn, data_dir)
    assert tpch.check_result(qn, got, want), (
        f"ingested {qn} diverges\n got[:3]={got[:3]}\nwant[:3]={want[:3]}")


def test_ingested_plan_runs_on_device(data_dir):
    text = open(os.path.join(FIXTURES, "q3.txt")).read()
    df = ingest_spark_plan(text, _session(), _tables(data_dir))
    report = df._physical().explain()
    assert "!Exec" not in report, report   # nothing fell off the TPU


def test_unknown_operator_raises():
    with pytest.raises(SparkPlanParseError):
        ingest_spark_plan("*(1) FancyNewExec [x#1]\n", _session(), {})


def test_host_oracle_agrees(data_dir):
    text = open(os.path.join(FIXTURES, "q6.txt")).read()
    df = ingest_spark_plan(text, _session(), _tables(data_dir))
    got = df.collect()
    want = df.collect_host()
    assert len(got) == len(want) == 1
    assert abs(got[0][0] - want[0][0]) < 1e-6 * abs(want[0][0])


def test_misaligned_operator_line_raises():
    """ISSUE 2 satellite: a line that looks like an operator but fails
    the multiple-of-3 indentation check must raise, not silently drop
    the operator (a vanished Filter = silently wrong results)."""
    bad = ("*(1) Project [x#1]\n"
           "  +- Filter (x#1 > 2)\n"           # 2-space indent: malformed
           "      +- FileScan parquet [x#1]\n")
    with pytest.raises(SparkPlanParseError, match="indentation"):
        ingest_spark_plan(bad, _session(), {})


def test_scan_missing_columns_raises(data_dir):
    """ISSUE 2 satellite: a captured scan that wants columns the local
    file lacks must raise naming them, instead of silently narrowing
    the scan to a DIFFERENT query."""
    text = ("*(1) FileScan parquet [l_shipdate#26,no_such_col#99] "
            "Batched: true, Format: Parquet, Location: "
            "InMemoryFileIndex[file:/data/tpch/lineitem], "
            "ReadSchema: struct<l_shipdate:date>\n")
    with pytest.raises(SparkPlanParseError, match="no_such_col"):
        ingest_spark_plan(text, _session(), _tables(data_dir))
