"""Native Pallas kernel layer (ISSUE 11 tentpole): bit-identity parity,
gating, and fallback chaos.

Every native kernel (ops/native.py) must be BIT-IDENTICAL to its
jax.numpy twin across the dtype ladder — including -0.0/NaN float edge
cases — individually gateable, and `native.enabled=false` must restore
today's code paths byte-for-byte. On this CPU backend the kernels run
through the Pallas interpreter (``native.forced`` sets
SRT_NATIVE_INTERPRET for the scope); on a real TPU the same tests
exercise the Mosaic lowering.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import jax.ops

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.ops import kernel_cache as kc
from spark_rapids_tpu.ops import kernels, native


def _bits(a: np.ndarray) -> np.ndarray:
    """Bit view for exact comparison (distinguishes -0.0 and NaN
    payloads)."""
    a = np.asarray(a)
    return a if a.dtype == np.bool_ else a.view(np.uint8)


def assert_bit_equal(twin, got, msg=None):
    t, g = np.asarray(twin), np.asarray(got)
    assert t.dtype == g.dtype and t.shape == g.shape, (msg, t.dtype,
                                                      g.dtype)
    assert np.array_equal(_bits(t), _bits(g)), (msg, t[:8], g[:8])


# ---------------------------------------------------------------------------
# Kernel 1: stable radix rank
# ---------------------------------------------------------------------------

class TestRadixSort:
    @pytest.mark.parametrize("cap", [
        8, 12, 96,
        pytest.param(768, marks=pytest.mark.slow),
        pytest.param(1024, marks=pytest.mark.slow)])
    def test_stable_argsort_u32_bit_identical(self, cap):
        rng = np.random.default_rng(cap)
        with native.forced():
            for hi in (8, 2 ** 32):     # heavy ties and full range
                keys = jnp.asarray(rng.integers(0, hi, cap,
                                                dtype=np.uint32))
                # argsort returns int64 under x64; both are pure gather
                # indices at the call site, so compare values as i32.
                assert_bit_equal(
                    jnp.argsort(keys, stable=True).astype(jnp.int32),
                    native.stable_argsort_u32(keys),
                    f"cap={cap} hi={hi}")

    def test_radix_perm_multi_pass_parity(self):
        """The real call site: _radix_perm over several word passes
        (the multi-key LSD sort) native vs fallback."""
        rng = np.random.default_rng(3)
        cap = 384
        passes = [jnp.asarray(rng.integers(0, 9, cap, dtype=np.uint32))
                  for _ in range(3)]
        with native.forced():
            on = kernels._radix_perm(passes, cap)
        with native.forced(master=False):
            off = kernels._radix_perm(passes, cap)
        assert_bit_equal(off, on)

    def test_unstable_first_pass_keeps_twin(self):
        """The relaxed-tie unstable first pass has no unique answer, so
        the native path must not engage for it (later passes still
        may)."""
        rng = np.random.default_rng(4)
        cap = 96
        passes = [jnp.asarray(rng.integers(0, 5, cap, dtype=np.uint32))]
        native.reset_counters()
        with native.forced():
            kernels._radix_perm(passes, cap, unstable_first=True)
            assert native.counters().get("nativeRadixSortTraces", 0) == 0
            kernels._radix_perm(passes, cap, unstable_first=False)
            assert native.counters().get("nativeRadixSortTraces", 0) == 1

    def test_float_domain_passes_keep_twin(self):
        """TPU f64 sort keys stay in the float domain — only u32 word
        passes go native; the mixed-pass sort still matches."""
        rng = np.random.default_rng(5)
        cap = 24
        passes = [jnp.asarray(rng.integers(0, 3, cap, dtype=np.uint32)),
                  jnp.asarray(rng.normal(size=cap)),
                  jnp.asarray(rng.integers(0, 3, cap, dtype=np.uint32))]
        with native.forced():
            on = kernels._radix_perm(passes, cap)
        with native.forced(master=False):
            off = kernels._radix_perm(passes, cap)
        assert_bit_equal(off, on)


# ---------------------------------------------------------------------------
# Kernel 2: join probe
# ---------------------------------------------------------------------------

class TestJoinProbe:
    @pytest.mark.parametrize("cap_b,cap_p", [
        (8, 8), (16, 24), (96, 12),
        pytest.param(512, 768, marks=pytest.mark.slow)])
    def test_searchsorted_pair_bit_identical(self, cap_b, cap_p):
        rng = np.random.default_rng(cap_b + cap_p)
        b = np.sort(rng.integers(0, 2 ** 63, cap_b).astype(np.uint64))
        # The sort sentinel run every real build side carries.
        b[-2:] = np.uint64(0xFFFFFFFFFFFFFFFF)
        q = rng.choice(np.concatenate(
            [b, rng.integers(0, 2 ** 63, cap_p).astype(np.uint64)]),
            cap_p)
        bj, qj = jnp.asarray(b), jnp.asarray(q)
        with native.forced():
            lo_n, hi_n = native.searchsorted_u64_pair(bj, qj)
        assert_bit_equal(
            jnp.searchsorted(bj, qj, side="left").astype(jnp.int32), lo_n)
        assert_bit_equal(
            jnp.searchsorted(bj, qj, side="right").astype(jnp.int32),
            hi_n)

    def test_probe_ranges_end_to_end(self):
        """probe_ranges through real built sides (duplicate + null keys)
        native vs fallback."""
        from spark_rapids_tpu.columnar import dtypes as dt
        from spark_rapids_tpu.columnar.host import HostBatch
        from spark_rapids_tpu.columnar.wire import upload
        from spark_rapids_tpu.ops.join import build_side, probe_ranges
        rng = np.random.default_rng(11)
        build = HostBatch.from_pydict(
            [("k", dt.INT64)],
            {"k": [int(x) for x in rng.integers(0, 6, 40)]})
        pvals = [int(x) for x in rng.integers(0, 9, 64)]
        pvals[3] = None
        probe = HostBatch.from_pydict([("k", dt.INT64)], {"k": pvals})
        db, dp = upload(build), upload(probe)

        def run():
            built = build_side(db, [0])
            lo, counts, plive = probe_ranges(built, dp, [0])
            return (np.asarray(lo), np.asarray(counts),
                    np.asarray(plive))
        with native.forced():
            on = run()
        with native.forced(master=False):
            off = run()
        for a, b_ in zip(off, on):
            assert_bit_equal(a, b_)


# ---------------------------------------------------------------------------
# Kernel 3: RLE decode
# ---------------------------------------------------------------------------

RLE_POOLS = [
    ("int8", np.int8, [1, 2, -3]),
    ("int16", np.int16, [100, -2000]),
    ("int32", np.int32, [7, -9, 2 ** 30]),
    ("int64", np.int64, [2 ** 40, -5, 0]),
    ("float32", np.float32, [1.5, -0.0, np.nan, 0.0]),
    ("float64", np.float64, [np.nan, -0.0, 0.0, 3.25, np.inf]),
]


class TestRleDecode:
    @pytest.mark.parametrize("name,dtype,pool", RLE_POOLS,
                             ids=[p[0] for p in RLE_POOLS])
    def test_decode_bit_identical(self, name, dtype, pool):
        """Run tables built exactly like wire._try_rle (bit-view run
        detection), decoded native vs the searchsorted+gather twin —
        including -0.0 vs 0.0 and NaN-payload runs."""
        from spark_rapids_tpu.columnar.batch import bucket_capacity
        rng = np.random.default_rng(hash(name) % 2 ** 31)
        n = 50
        cap = bucket_capacity(n)
        v = np.asarray([pool[i] for i in
                        np.repeat(rng.choice(len(pool), 5), 10)], dtype)
        bits = v.view(np.int32 if dtype == np.float32 else np.int64) \
            if dtype in (np.float32, np.float64) else v
        st = np.empty(n, bool)
        st[0] = True
        np.not_equal(bits[1:], bits[:-1], out=st[1:])
        runs = int(st.sum())
        run_cap = bucket_capacity(max(runs, 1))
        sidx = np.flatnonzero(st)
        run_vals = np.zeros(run_cap, dtype)
        run_vals[:runs] = v[sidx]
        ends = np.full(run_cap, cap, np.int32)
        if runs > 1:
            ends[:runs - 1] = sidx[1:]
        ends[runs - 1] = n
        rv, re_ = jnp.asarray(run_vals), jnp.asarray(ends)
        rows = jnp.arange(cap, dtype=jnp.int32)
        ridx = jnp.searchsorted(re_, rows, side="right").astype(jnp.int32)
        twin = jnp.take(rv, ridx, mode="clip")
        twin = jnp.where(rows < n, twin, jnp.zeros_like(twin))
        with native.forced():
            got = native.rle_decode(rv, re_, cap,
                                    jnp.asarray(n, jnp.int32))
        assert_bit_equal(twin, got, name)

    def test_upload_path_engages_and_matches(self):
        """A sorted low-cardinality column through the REAL wire v2
        upload funnel: native decode on vs off, bit-identical device
        batches."""
        from spark_rapids_tpu.columnar import dtypes as dt
        from spark_rapids_tpu.columnar.host import HostBatch
        from spark_rapids_tpu.columnar import wire
        vals = [float(x) for x in np.repeat([1.5, 2.5, 3.5], 40)]
        hb = HostBatch.from_pydict([("v", dt.FLOAT64)], {"v": vals})

        def run():
            return np.asarray(wire.upload(hb).columns[0].data)
        native.reset_counters()
        with native.forced():
            on = run()
            assert native.counters().get("nativeRleDecodeTraces", 0) >= 1
        with native.forced(master=False):
            off = run()
        assert_bit_equal(off, on)

    def test_run_cap_bound_falls_back(self):
        """Run tables past native.rleDecode.maxRuns keep the twin."""
        from spark_rapids_tpu.config import TpuConf
        native.maybe_configure(TpuConf(
            {"spark.rapids.sql.native.rleDecode.maxRuns": 4}))
        try:
            assert native.rle_max_runs() == 4
        finally:
            native.maybe_configure(TpuConf())
        assert native.rle_max_runs() > 4


# ---------------------------------------------------------------------------
# Kernel 4: sorted-segment reduction
# ---------------------------------------------------------------------------

SEG_DTYPES = [np.bool_, np.int8, np.int16, np.int32, np.int64,
              np.float32, np.float64]


class TestSegmentReduce:
    @pytest.mark.parametrize("dtype", SEG_DTYPES,
                             ids=[np.dtype(d).name for d in SEG_DTYPES])
    @pytest.mark.parametrize("cap", [
        24,
        pytest.param(8, marks=pytest.mark.slow),
        pytest.param(768, marks=pytest.mark.slow)])
    def test_raw_reduce_bit_identical(self, dtype, cap):
        rng = np.random.default_rng(cap)
        gid = np.sort(rng.integers(0, max(cap // 3, 1), cap)) \
            .astype(np.int32)
        if dtype == np.bool_:
            vals = rng.integers(0, 2, cap).astype(np.bool_)
        elif np.issubdtype(dtype, np.integer):
            info = np.iinfo(dtype)
            vals = rng.integers(info.min, info.max, cap).astype(dtype)
        else:
            vals = rng.choice(np.asarray(
                [1.5, -0.0, 0.0, np.inf, -np.inf, 3.7], dtype), cap)
        vj, gj = jnp.asarray(vals), jnp.asarray(gid)
        with native.forced():
            if dtype != np.bool_:
                got = native.segment_sum_sorted(vj, gj, cap)
                if np.issubdtype(dtype, np.integer):
                    assert got is not None, "int sums must be native"
                    assert_bit_equal(jax.ops.segment_sum(
                        vj, gj, num_segments=cap), got, "sum")
                else:
                    assert got is None, \
                        "float sums must keep the twin (order changes " \
                        "rounding)"
            for kind, red in (("min", jax.ops.segment_min),
                              ("max", jax.ops.segment_max)):
                got = native.segment_minmax_sorted(vj, gj, cap, kind)
                assert got is not None
                assert_bit_equal(red(vj, gj, num_segments=cap), got, kind)

    @pytest.mark.parametrize("kind", ["sum", "min", "max"])
    def test_segment_reduce_null_discipline(self, kind):
        """The full kernels.segment_reduce wrapper (Spark null/NaN
        discipline) native vs fallback, with NaN + -0.0 + nulls."""
        cap = 48
        rng = np.random.default_rng(9)
        vals = rng.choice(np.asarray(
            [1.5, -0.0, 0.0, np.nan, np.inf, -2.25]), cap)
        validity = rng.integers(0, 4, cap) > 0
        gid = np.sort(rng.integers(0, 12, cap)).astype(np.int32)
        args = (jnp.asarray(vals), jnp.asarray(validity),
                jnp.asarray(gid), cap, kind)
        with native.forced():
            agg_on, cnt_on = kernels.segment_reduce(*args)
        with native.forced(master=False):
            agg_off, cnt_off = kernels.segment_reduce(*args)
        assert_bit_equal(agg_off, agg_on, kind)
        assert_bit_equal(cnt_off, cnt_on, "counts")

    def test_int_sum_wraparound_parity(self):
        """int32 overflow wraps identically (two's complement)."""
        cap = 12
        vals = jnp.asarray(np.full(cap, 2 ** 30, np.int32))
        gid = jnp.zeros(cap, jnp.int32)
        with native.forced():
            got = native.segment_sum_sorted(vals, gid, cap)
        assert_bit_equal(jax.ops.segment_sum(vals, gid, num_segments=cap),
                         got)


# ---------------------------------------------------------------------------
# Gating, cache coherence, and the kill-switch contract
# ---------------------------------------------------------------------------

class TestGating:
    def test_cpu_defaults_to_fallback(self, monkeypatch):
        """Without the interpreter forced, a CPU backend never engages
        native kernels — the 'CPU runs no-op to the fallback' clause."""
        if jax.default_backend() == "tpu":
            pytest.skip("TPU backend: native is genuinely available")
        monkeypatch.delenv("SRT_NATIVE_INTERPRET", raising=False)
        assert not native.available()
        assert not native.kernel_enabled("radixSort")
        assert native.fingerprint() == ()

    def test_conf_keys_gate_individually(self, monkeypatch):
        from spark_rapids_tpu.config import TpuConf
        monkeypatch.setenv("SRT_NATIVE_INTERPRET", "1")
        native.maybe_configure(TpuConf(
            {"spark.rapids.sql.native.radixSort.enabled": False}))
        try:
            assert not native.kernel_enabled("radixSort")
            assert native.kernel_enabled("joinProbe")
        finally:
            native.maybe_configure(TpuConf())

    def test_master_kill_switch(self, monkeypatch):
        from spark_rapids_tpu.config import TpuConf
        monkeypatch.setenv("SRT_NATIVE_INTERPRET", "1")
        native.maybe_configure(TpuConf(
            {"spark.rapids.sql.native.enabled": False}))
        try:
            assert not any(native.kernel_enabled(k)
                           for k in native.KERNELS)
            assert native.fingerprint() == ()
        finally:
            native.maybe_configure(TpuConf())

    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("SRT_NATIVE_INTERPRET", "1")
        monkeypatch.setenv("SRT_NATIVE", "0")
        assert not native.master_enabled()
        assert native.fingerprint() == ()

    def test_fingerprint_keys_kernel_cache(self):
        """Toggling a native gate must MISS the kernel cache, never
        serve a program traced under the other setting."""
        calls = []

        def builder():
            calls.append(1)
            return lambda: None
        key = ("native-gate-test", id(calls))
        with native.forced():
            kc.lookup("t", key, builder)
        with native.forced(master=False):
            kc.lookup("t", key, builder)
        assert len(calls) == 2, "same key served across a gate toggle"


# ---------------------------------------------------------------------------
# End-to-end: the 11-query sweep + chaos (fallback matrix green on CPU)
# ---------------------------------------------------------------------------

def _session(native_on: bool, chaos: str = "") -> TpuSession:
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.native.enabled", native_on)
    # Cold scans so the upload/decode funnel (the RLE kernel's call
    # site) actually runs.
    s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    if chaos:
        s.set("spark.rapids.sql.test.faults", chaos)
        s.set("spark.rapids.sql.test.faults.seed", 7)
        s.set("spark.rapids.sql.retry.backoffMs", 1)
    return s


def _tpch_dir(tmp_path_factory):
    from spark_rapids_tpu.benchmarks import tpch
    d = getattr(_tpch_dir, "_dir", None)
    if d is None:
        d = str(tmp_path_factory.mktemp("native_tpch"))
        tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
        _tpch_dir._dir = d
    return d


def _suites_dir(tmp_path_factory):
    from spark_rapids_tpu.benchmarks import suites
    d = getattr(_suites_dir, "_dir", None)
    if d is None:
        d = str(tmp_path_factory.mktemp("native_suites"))
        suites.generate(d, scale=0.01, files_per_table=2)
        _suites_dir._dir = d
    return d


_TPCH = ["q1",
         pytest.param("q6", marks=pytest.mark.slow),
         pytest.param("q3", marks=pytest.mark.slow),
         pytest.param("q5", marks=pytest.mark.slow),
         pytest.param("q12", marks=pytest.mark.slow),
         pytest.param("q14", marks=pytest.mark.slow)]
_SUITES = [pytest.param("repart", marks=pytest.mark.slow),
           pytest.param("q67", marks=pytest.mark.slow),
           pytest.param("xbb_q5", marks=pytest.mark.slow),
           pytest.param("ds_q3", marks=pytest.mark.slow),
           pytest.param("xbb_q12", marks=pytest.mark.slow)]


class TestEndToEnd:
    @pytest.mark.parametrize("qname", _TPCH)
    def test_tpch_native_on_off_bit_identical(self, qname,
                                              tmp_path_factory):
        from spark_rapids_tpu.benchmarks import tpch
        d = _tpch_dir(tmp_path_factory)
        native.reset_counters()
        with native.forced():
            on = tpch.QUERIES[qname](_session(True), d).collect()
            if qname == "q1":
                # The sweep must not pass vacuously: q1's grouping
                # sorts trace the radix kernel at minimum. (The native
                # fingerprint is part of every kernel-cache key, so the
                # first native-on q1 in a process always traces fresh —
                # non-native runs of q1 elsewhere in the suite cannot
                # have seeded these entries.)
                assert native.counters().get(
                    "nativeRadixSortTraces", 0) > 0
        off = tpch.QUERIES[qname](_session(False), d).collect()
        assert on == off

    @pytest.mark.parametrize("qname", _SUITES)
    def test_suites_native_on_off_bit_identical(self, qname,
                                                tmp_path_factory):
        from spark_rapids_tpu.benchmarks import suites
        d = _suites_dir(tmp_path_factory)
        with native.forced():
            on = suites.QUERIES[qname](_session(True), d).collect()
        off = suites.QUERIES[qname](_session(False), d).collect()
        assert on == off

    def test_chaos_native_fallback_matrix_green(self, tmp_path_factory):
        """Seeded oom+transient schedule under native kernels: the
        recovery ladder runs THROUGH the native dispatch funnel and the
        result stays bit-identical to the clean native-off run."""
        from spark_rapids_tpu.benchmarks import tpch
        d = _tpch_dir(tmp_path_factory)
        clean = tpch.QUERIES["q1"](_session(False), d).collect()
        chaos = "oom@kernel:1,transient@upload:1"
        with native.forced():
            df = tpch.QUERIES["q1"](_session(True, chaos), d)
            got = df.collect()
            m = df.metrics().get("Recovery@query", {})
            assert m.get("faultsInjected", 0) >= 1, m
        assert got == clean
