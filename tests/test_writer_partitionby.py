"""Dynamic-partition writes + write stats (VERDICT r4 item 5;
GpuFileFormatWriter.scala:338, BasicColumnarWriteStatsTracker.scala:180)."""

import os

import pytest

from spark_rapids_tpu import FLOAT64, INT64, STRING
from spark_rapids_tpu.api.dataframe import TpuSession


def _df(s):
    return s.create_dataframe(
        {"k": ["a", "b", "a", "c", "b", "a"],
         "n": [1, 2, 3, 4, 5, 6],
         "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]},
        [("k", STRING), ("n", INT64), ("v", FLOAT64)],
        num_partitions=2)


def test_partition_by_layout_and_stats(tmp_path):
    s = TpuSession()
    out = str(tmp_path / "out")
    w = _df(s).write
    stats = w.partition_by("k").parquet(out)
    dirs = sorted(d for d in os.listdir(out) if d.startswith("k="))
    assert dirs == ["k=a", "k=b", "k=c"]
    # Partition column is NOT in the files (Hive layout). Read the bare
    # file (ParquetFile), not read_table: pyarrow >= 22 re-infers the
    # hive partition column from the k=a path segment even for a single
    # file, which would mask a writer that wrongly kept the column.
    import pyarrow.parquet as papq
    files = [os.path.join(out, "k=a", f)
             for f in os.listdir(os.path.join(out, "k=a"))]
    t = papq.ParquetFile(files[0]).read()
    assert t.schema.names == ["n", "v"]
    assert stats["numOutputRows"] == 6
    assert stats["numParts"] == 3
    assert stats["numFiles"] >= 3
    assert stats["numOutputBytes"] > 0
    # Values routed to the right directory.
    rows_a = sum(papq.read_table(os.path.join(out, "k=a", f)).num_rows
                 for f in os.listdir(os.path.join(out, "k=a")))
    assert rows_a == 3


def test_partition_by_roundtrip_read(tmp_path):
    s = TpuSession()
    out = str(tmp_path / "rt")
    _df(s).write.partition_by("k").parquet(out)
    parts = []
    for d in sorted(os.listdir(out)):
        full = os.path.join(out, d)
        if not os.path.isdir(full):
            continue
        for f in sorted(os.listdir(full)):
            parts.append(os.path.join(full, f))
    back = s.read.parquet(*parts).collect()
    assert sorted(r[0] for r in back) == [1, 2, 3, 4, 5, 6]


def test_plain_write_stats(tmp_path):
    s = TpuSession()
    out = str(tmp_path / "plain")
    w = _df(s).write
    stats = w.parquet(out)
    assert stats["numOutputRows"] == 6
    assert stats["numFiles"] == 2          # one per engine partition
    assert stats["numParts"] == 0
