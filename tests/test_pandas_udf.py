"""Pandas-UDF exec family (VERDICT r4 item 8): map_in_pandas,
apply_in_pandas (grouped map), cogrouped map, grouped-agg pandas UDFs —
host islands inside device plans with a bounded worker pool
(GpuMapInPandasExec / GpuFlatMapGroupsInPandasExec /
GpuCoGroupedMapInPandasExec / GpuAggregateInPandasExec,
PythonWorkerSemaphore)."""

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import FLOAT64, INT64, STRING
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.plan.logical import col


def _session():
    return TpuSession()


def _df(s, n=200, parts=4, seed=3):
    rng = np.random.default_rng(seed)
    return s.create_dataframe(
        {"g": rng.integers(0, 9, n).tolist(),
         "v": np.round(rng.normal(size=n), 6).tolist()},
        [("g", INT64), ("v", FLOAT64)], num_partitions=parts)


def test_map_in_pandas():
    s = _session()

    def doubler(frames):
        for pdf in frames:
            out = pdf.copy()
            out["v2"] = out.v * 2.0
            yield out[["g", "v2"]]

    df = _df(s).map_in_pandas(doubler,
                              [("g", INT64), ("v2", FLOAT64)])
    got = sorted(df.collect())
    want = sorted(df.collect_host())
    assert got == want
    assert len(got) == 200
    assert all(abs(r[1]) < 20 for r in got)


def test_apply_in_pandas_grouped_map():
    s = _session()

    def center(pdf):
        out = pdf.copy()
        out["v"] = out.v - out.v.mean()
        return out

    df = _df(s).group_by("g").apply_in_pandas(
        center, [("g", INT64), ("v", FLOAT64)])
    got = sorted(df.collect())
    want = sorted(df.collect_host())
    assert len(got) == 200
    for a, b in zip(got, want):
        assert a[0] == b[0] and abs(a[1] - b[1]) < 1e-9
    # Per-group means are ~0 after centering.
    pdf = pd.DataFrame(got, columns=["g", "v"])
    assert pdf.groupby("g").v.mean().abs().max() < 1e-9


def test_cogrouped_map():
    s = _session()
    left = _df(s, n=60, seed=1)
    right = s.create_dataframe(
        {"k": [0, 1, 2, 3, 42], "w": [10.0, 20.0, 30.0, 40.0, 99.0]},
        [("k", INT64), ("w", FLOAT64)], num_partitions=2)

    def merge(lp, rp):
        n = len(lp)
        w = float(rp.w.iloc[0]) if len(rp) else -1.0
        g = int(lp.g.iloc[0]) if n else \
            (int(rp.k.iloc[0]) if len(rp) else -1)
        return pd.DataFrame({"g": [g], "n": [n], "w": [w]})

    df = left.group_by("g").cogroup(right.group_by("k")) \
        .apply_in_pandas(merge, [("g", INT64), ("n", INT64),
                                 ("w", FLOAT64)])
    got = sorted(df.collect())
    want = sorted(df.collect_host())
    assert got == want
    by_g = {r[0]: r for r in got}
    assert 42 in by_g and by_g[42][1] == 0      # right-only key
    assert by_g[0][2] == 10.0                   # matched key
    assert any(r[2] == -1.0 for r in got)       # left-only keys


def test_agg_in_pandas():
    s = _session()
    df = _df(s).group_by("g").agg_in_pandas(
        med=("v", lambda series: float(series.median()), FLOAT64),
        cnt=("v", lambda series: int(len(series)), INT64))
    got = sorted(df.collect())
    want = sorted(df.collect_host())
    assert got == want
    assert sum(r[2] for r in got) == 200


def test_worker_pool_is_bounded():
    import threading
    s = _session()
    s.set("spark.rapids.python.concurrentPythonWorkers", 2)
    active, peak = [], []
    lock = threading.Lock()

    def slow(pdf):
        import time
        with lock:
            active.append(1)
            peak.append(len(active))
        time.sleep(0.02)
        with lock:
            active.pop()
        return pdf

    _df(s, n=400, parts=1).group_by("g").apply_in_pandas(
        slow, [("g", INT64), ("v", FLOAT64)]).collect()
    assert max(peak) <= 2


def test_cogroup_null_keys_collide():
    """Regression (ISSUE 2 satellite): float-NaN group keys from the two
    cogrouped sides must land in ONE cogrouped call (Spark null-key
    grouping), not pair each side's null group with an empty frame —
    pandas returns nan keys under dropna=False, and two nans from two
    separate groupbys are neither equal nor same-hash."""
    s = _session()
    left = s.create_dataframe(
        {"k": [1.0, None, None, 2.0], "v": [10.0, 20.0, 30.0, 40.0]},
        [("k", FLOAT64), ("v", FLOAT64)])
    right = s.create_dataframe(
        {"k": [None, 3.0], "w": [100.0, 200.0]},
        [("k", FLOAT64), ("w", FLOAT64)])

    def merge(lpdf, rpdf):
        # (left rows, right rows) per cogrouped key: the null key must
        # see BOTH sides' rows in the same call.
        return pd.DataFrame({"nl": [float(len(lpdf))],
                             "nr": [float(len(rpdf))]})

    df = left.group_by("k").cogroup(right.group_by("k")).apply_in_pandas(
        merge, [("nl", FLOAT64), ("nr", FLOAT64)])
    got = sorted(df.collect())
    # Keys: 1.0 (1,0), 2.0 (1,0), 3.0 (0,1), null (2,1) — four calls,
    # with the two left nulls and one right null cogrouped together.
    assert got == [(0.0, 1.0), (1.0, 0.0), (1.0, 0.0), (2.0, 1.0)]
    assert df.collect_host() is not None  # host path tolerates it too
