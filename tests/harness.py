"""Dual-path compare harness (ref: SparkQueryCompareTestSuite.scala:153-161).

The reference runs every test body twice — CPU Spark vs GPU plugin — and
compares collected results. Here the two engines are the host (numpy)
expression/operator path and the device (jnp under jit) path; both must
produce identical python-level results, with float tolerance knobs mirroring
``approximate_float``.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np

from spark_rapids_tpu.benchmarks.compare import (     # noqa: F401
    compare_results, first_mismatch, sort_key, values_close)
from spark_rapids_tpu.columnar.host import HostBatch, host_to_device, \
    device_to_host
from spark_rapids_tpu.exprs.base import (
    Expression, eval_exprs, eval_exprs_host)


def assert_results_equal(got, want, sort: bool = False,
                         rel_tol: float = 1e-6, abs_tol: float = 1e-9,
                         msg: str = "oracle compare"):
    """Generalized oracle comparison (BenchUtils.compareResults analog,
    benchmarks/compare.py): sorted-rows option for computed-float ORDER
    BY, dtype-aware epsilon (floats/dates), None-aware exact compare
    elsewhere. The assertion message pinpoints the first divergence."""
    bad = first_mismatch(got, want, sort=sort, rel_tol=rel_tol,
                         abs_tol=abs_tol)
    assert bad is None, f"{msg}: first mismatch {bad!r}"


def assert_rows_equal(actual, expected, approx_float: bool = False,
                      msg: str = ""):
    assert len(actual) == len(expected), \
        f"{msg}: row count {len(actual)} != {len(expected)}"
    for r, (a_row, e_row) in enumerate(zip(actual, expected)):
        assert len(a_row) == len(e_row), f"{msg}: row {r} width differs"
        for c, (a, e) in enumerate(zip(a_row, e_row)):
            if a is None or e is None:
                assert a is None and e is None, \
                    f"{msg}: [{r}][{c}] {a!r} != {e!r}"
                continue
            if isinstance(e, float):
                if math.isnan(e):
                    assert isinstance(a, float) and math.isnan(a), \
                        f"{msg}: [{r}][{c}] {a!r} != NaN"
                elif approx_float:
                    assert a == e or abs(a - e) <= 1e-6 * max(
                        1.0, abs(e)), f"{msg}: [{r}][{c}] {a!r} !~ {e!r}"
                else:
                    assert a == e, f"{msg}: [{r}][{c}] {a!r} != {e!r}"
            else:
                assert a == e, f"{msg}: [{r}][{c}] {a!r} != {e!r}"


def check_exprs(exprs: Sequence[Expression], batch: HostBatch,
                expected: Optional[Sequence[tuple]] = None,
                approx_float: bool = False):
    """Evaluate on host and device (jit), compare, return device rows."""
    host_out = eval_exprs_host(exprs, batch).to_pylist()

    dev_in = host_to_device(batch)

    if all(e.jittable for e in exprs):
        run = jax.jit(lambda b: eval_exprs(exprs, b))
    else:
        # Expression-level CPU island: runs eagerly with host roundtrips.
        run = lambda b: eval_exprs(exprs, b)

    dev_batch = run(dev_in)
    dev_out = device_to_host(dev_batch).to_pylist()

    assert_rows_equal(dev_out, host_out, approx_float,
                      "device vs host engine")
    if expected is not None:
        assert_rows_equal(dev_out, list(expected), approx_float,
                          "device vs oracle")
    return dev_out


def check_expr(expr: Expression, batch: HostBatch,
               expected: Optional[Sequence] = None,
               approx_float: bool = False):
    exp = None if expected is None else [(e,) for e in expected]
    rows = check_exprs([expr], batch, exp, approx_float)
    return [r[0] for r in rows]


# ---------------------------------------------------------------------------
# Pure-python scalar Murmur3_x86_32 oracle (independent of the vector impl)
# ---------------------------------------------------------------------------

_M = 0xFFFFFFFF


def _py_rotl(x, r):
    return ((x << r) | (x >> (32 - r))) & _M


def _py_mix_k1(k1):
    k1 = (k1 * 0xCC9E2D51) & _M
    k1 = _py_rotl(k1, 15)
    return (k1 * 0x1B873593) & _M


def _py_mix_h1(h1, k1):
    # k1 must already be mixed by the caller (matches Murmur3_x86_32).
    h1 ^= k1
    h1 = _py_rotl(h1, 13)
    return (h1 * 5 + 0xE6546B64) & _M


def _py_fmix(h1, length):
    h1 ^= length
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _M
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _M
    h1 ^= h1 >> 16
    return h1


def py_hash_int(value, seed):
    h1 = _py_mix_h1(seed & _M, _py_mix_k1(value & _M))
    return _py_fmix(h1, 4)


def py_hash_long(value, seed):
    v = value & 0xFFFFFFFFFFFFFFFF
    low = v & _M
    high = (v >> 32) & _M
    h1 = _py_mix_h1(seed & _M, _py_mix_k1(low))
    h1 = _py_mix_h1(h1, _py_mix_k1(high))
    return _py_fmix(h1, 8)


def py_hash_bytes(data: bytes, seed):
    h1 = seed & _M
    nblocks = len(data) // 4
    for i in range(nblocks):
        word = int.from_bytes(data[i * 4:(i + 1) * 4], "little")
        h1 = _py_mix_h1(h1, _py_mix_k1(word))
    for i in range(nblocks * 4, len(data)):
        b = data[i]
        if b >= 128:
            b -= 256  # signed byte, like the JVM
        h1 = _py_mix_h1(h1, _py_mix_k1(b & _M))
    return _py_fmix(h1, len(data))


def to_signed32(v):
    v &= _M
    return v - (1 << 32) if v >= (1 << 31) else v
