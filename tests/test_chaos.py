"""Chaos suite: the recovery machinery under deterministic fault
injection (ISSUE 2; the continuous-verification analog of the
reference's spill/retry + CPU-fallback guarantees).

End-to-end: TPC-H queries run under seeded OOM + transient + corruption
schedules and must return results BIT-IDENTICAL to the fault-free run
with ``faultsInjected > 0`` and zero unhandled exceptions. Unit level:
every escalation rung (spill-some, spill-all, shrink, host-fallback)
provably fires, in order.
"""

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch, host_to_device
from spark_rapids_tpu.memory import oom
from spark_rapids_tpu.memory.stores import BufferCatalog, StorageTier


@pytest.fixture(autouse=True)
def clean_fault_state():
    """Disarm + reset the process-global registry and the degraded batch
    target around every test (both leak across queries by design)."""
    faults.configure("")
    faults.reset_counters()
    oom.reset_degradation()
    yield
    faults.configure("")
    faults.reset_counters()
    oom.reset_degradation()


# ---------------------------------------------------------------------------
# TPC-H under seeded fault schedules: bit-identical to the fault-free run
# ---------------------------------------------------------------------------

QUERIES = ["q1", "q6", "q3"]

# Each schedule mixes fault kinds across dispatch funnels. OOM counts stay
# at 1 per site so the ladder recovers without reaching the host-fallback
# rung (host and device float summation orders may legitimately differ in
# the last ulp; bit-identity is the DEVICE-recovery contract here —
# host-fallback correctness is proven separately below).
SCHEDULES = {
    "oom": "oom@upload:1,oom@kernel:1,oom@concat:1",
    "transient": ("transient@exchange.flush:1,transient@download:1,"
                  "oom@kernel:1"),
    "corrupt": "corrupt@wire:2,oom@upload:1,transient@exchange.serve:1",
}


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_chaos"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


def _session(chaos: str = "", spill_dir: str = ""):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    # Explicitly (dis)arm: the registry is process-global and the
    # baseline run must never inherit a previous query's schedule.
    s.set("spark.rapids.sql.test.faults", chaos)
    s.set("spark.rapids.sql.test.faults.seed", 7)
    s.set("spark.rapids.sql.retry.backoffMs", 1)
    if chaos:
        # Pressure the spill tiers so disk frames (the corruption
        # surface) actually exist and spill rungs have victims; disable
        # the device scan cache so the upload funnel (and its fault
        # site) runs on every query instead of serving cached batches.
        s.set("spark.rapids.memory.tpu.budgetBytes", 1 << 19)
        s.set("spark.rapids.memory.host.spillStorageSize", 1 << 18)
        s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
        if spill_dir:
            s.set("spark.rapids.memory.spill.dir", spill_dir)
    return s


@pytest.fixture(scope="module")
def baselines(data_dir):
    """Fault-free device results per query (the bit-identity oracle)."""
    out = {}
    for qn in QUERIES:
        out[qn] = tpch.QUERIES[qn](_session(), data_dir).collect()
    return out


@pytest.mark.parametrize("schedule", sorted(SCHEDULES))
@pytest.mark.parametrize("qname", QUERIES)
def test_tpch_bit_identical_under_faults(qname, schedule, baselines,
                                         data_dir, tmp_path):
    faults.reset_counters()
    df = tpch.QUERIES[qname](_session(SCHEDULES[schedule],
                                      str(tmp_path)), data_dir)
    got = df.collect()          # zero unhandled exceptions, by contract
    c = faults.counters()
    assert c.get("faultsInjected", 0) > 0, c
    # Bit-identical: tuple equality is exact — floats compare by value
    # (every recovery path re-runs the identical pure computation).
    assert got == baselines[qname], (
        f"{qname} under {schedule!r} diverged from the fault-free run")


def test_metrics_surface_recovery_counters(data_dir):
    df = tpch.QUERIES["q6"](_session("oom@upload:1"), data_dir)
    df.collect()
    m = df.metrics()
    rec = m.get("Recovery@query")
    assert rec is not None and rec.get("faultsInjected", 0) >= 1, m


# ---------------------------------------------------------------------------
# Shuffle-transport chaos (ISSUE 6): a lost/corrupt REMOTE shard on the
# hostfile transport flows through lineage-scoped stage recompute — one
# stage rewrites its spool, the query never whole-query-retries.
# ---------------------------------------------------------------------------

def _hostfile_session(chaos: str, spool: str) -> TpuSession:
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.shuffle.transport", "hostfile")
    s.set("spark.rapids.sql.shuffle.transport.hostfile.dir", spool)
    s.set("spark.rapids.sql.test.faults", chaos)
    s.set("spark.rapids.sql.test.faults.seed", 7)
    s.set("spark.rapids.sql.retry.backoffMs", 1)
    return s


def test_lost_remote_shard_recomputes_exactly_one_stage(
        baselines, data_dir, tmp_path):
    """``lostshard@transport`` deletes the shard at rest and raises
    owner-tagged: recovery must invalidate ONLY the owning exchange's
    stage, rewrite its spool, and produce bit-identical results — with
    zero whole-query retries."""
    from spark_rapids_tpu.parallel import transport as T
    faults.reset_counters()
    T.reset_counters()
    got = tpch.QUERIES["q3"](
        _hostfile_session("lostshard@transport:1", str(tmp_path)),
        data_dir).collect()
    assert got == baselines["q3"]
    c = faults.counters()
    assert c.get("faultsInjected.lostshard@transport") == 1, c
    assert c.get("stageRecomputes") == 1, c
    # The lineage detail names exactly ONE recomputed stage.
    details = [k for k in c if k.startswith("stageRecomputes.stage")]
    assert len(details) == 1 and c[details[0]] == 1, c
    # Scoped recovery, not the whole-query rung.
    assert c.get("retriesAttempted", 0) == 0, c
    assert T.counters().get("remoteShardsLost") == 1


def test_corrupt_remote_shard_refetches_without_recompute(
        baselines, data_dir, tmp_path):
    """``corrupt@transport:1`` flips a byte of one fetched frame: the
    CRC detects it and ONE refetch recovers (the spool data is intact)
    — no stage recompute, bit-identical results."""
    from spark_rapids_tpu.parallel import transport as T
    faults.reset_counters()
    T.reset_counters()
    got = tpch.QUERIES["q3"](
        _hostfile_session("corrupt@transport:1", str(tmp_path)),
        data_dir).collect()
    assert got == baselines["q3"]
    c = faults.counters()
    assert c.get("faultsInjected.corrupt@transport") == 1, c
    assert c.get("remoteShardRefetches") == 1, c
    assert c.get("stageRecomputes", 0) == 0, c
    assert T.counters().get("remoteShardRefetches") == 1


def test_persistently_corrupt_shard_escalates_to_stage_recompute(
        baselines, data_dir, tmp_path):
    """``corrupt@transport:2`` corrupts the SAME shard's read and its
    refetch: the data at rest is effectively gone, so the CRC failure
    escalates owner-tagged to the stage-recompute rung, which rewrites
    the spool — still bit-identical."""
    faults.reset_counters()
    got = tpch.QUERIES["q3"](
        _hostfile_session("corrupt@transport:2", str(tmp_path)),
        data_dir).collect()
    assert got == baselines["q3"]
    c = faults.counters()
    assert c.get("corruptionsDetected", 0) >= 2, c
    assert c.get("stageRecomputes") == 1, c


def test_mixed_transport_schedule_bit_identical(baselines, data_dir,
                                                tmp_path):
    """Loss + corruption + a transient in one schedule, still
    bit-identical through the layered recovery."""
    faults.reset_counters()
    got = tpch.QUERIES["q3"](
        _hostfile_session(
            "lostshard@transport:1,corrupt@transport:1,"
            "transient@transport.write:1", str(tmp_path)),
        data_dir).collect()
    assert got == baselines["q3"]
    c = faults.counters()
    assert c.get("faultsInjected", 0) >= 3, c


# ---------------------------------------------------------------------------
# Escalation ladder unit tests: each rung fires, in order
# ---------------------------------------------------------------------------

def _batch(seed, n=64):
    rng = np.random.default_rng(seed)
    hb = HostBatch.from_pydict(
        [("a", dt.INT64), ("s", dt.STRING)],
        {"a": rng.integers(0, 1000, n).tolist(),
         "s": [f"row{seed}_{i}" for i in range(n)]})
    return host_to_device(hb)


def _oom_error():
    return RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                        "99 bytes")


class TestEscalationLadder:
    def test_rungs_fire_in_order(self, tmp_path):
        cat = BufferCatalog(device_budget_bytes=1 << 30,
                            spill_dir=str(tmp_path))
        ids = [cat.add_batch(_batch(i)) for i in range(4)]
        for bid in ids:
            cat.release(bid)
        oom.set_active_catalog(cat)
        calls = []
        try:
            def flaky():
                calls.append(1)
                if len(calls) <= 3:     # initial + first two rungs fail
                    raise _oom_error()
                return "ok"

            assert oom.retry_on_oom(flaky) == "ok"
        finally:
            oom.set_active_catalog(None)
            cat.close()
        # initial attempt + one retry per acting rung, in ladder order.
        assert calls == [1, 1, 1, 1]
        assert oom.last_ladder == [oom.RUNG_SPILL_SOME,
                                   oom.RUNG_SPILL_ALL,
                                   oom.RUNG_SHRINK]
        assert oom.degrade_factor() == 2

    def test_spill_some_spills_half_then_spill_all_rest(self, tmp_path):
        cat = BufferCatalog(device_budget_bytes=1 << 30,
                            spill_dir=str(tmp_path))
        ids = [cat.add_batch(_batch(i)) for i in range(4)]
        for bid in ids:
            cat.release(bid)
        freed = cat.spill_some()
        tiers = [cat.tier_of(i) for i in ids]
        assert freed > 0
        assert StorageTier.HOST in tiers       # spilled some...
        assert StorageTier.DEVICE in tiers     # ...but not everything
        assert cat.handle_oom() > 0            # spill-all takes the rest
        assert all(cat.tier_of(i) == StorageTier.HOST for i in ids)
        cat.close()

    def test_shrink_degrades_batch_target_boundedly(self):
        target = 4 << 20
        assert oom.effective_batch_target(target) == target
        assert oom.shrink_batch_target()
        assert oom.effective_batch_target(target) == target // 2
        while oom.shrink_batch_target():
            pass
        assert oom.degrade_factor() == 8       # bounded
        assert oom.effective_batch_target(1 << 10) == 1 << 12  # floor
        oom.reset_degradation()
        assert oom.effective_batch_target(target) == target

    def test_exhausted_ladder_raises_with_rung_trail(self, tmp_path):
        cat = BufferCatalog(device_budget_bytes=1 << 30,
                            spill_dir=str(tmp_path))
        # 3 buffers: spill-some takes ~half, spill-all takes the rest —
        # every rung has something to act on.
        for bid in [cat.add_batch(_batch(i)) for i in range(3)]:
            cat.release(bid)
        oom.set_active_catalog(cat)
        try:
            def always():
                raise _oom_error()

            with pytest.raises(oom.OomRetryExhausted) as ei:
                oom.retry_on_oom(always)
        finally:
            oom.set_active_catalog(None)
            cat.close()
        assert ei.value.rungs == [oom.RUNG_SPILL_SOME, oom.RUNG_SPILL_ALL,
                                  oom.RUNG_SHRINK]
        # No OOM marker: an enclosing retry_on_oom must propagate it
        # instead of re-running the ladder.
        assert not oom.is_oom_error(ei.value)

    def test_nothing_actionable_reraises_original(self):
        # No catalog, degradation already at its bound: every rung is
        # skipped and the ORIGINAL error propagates unchanged.
        while oom.shrink_batch_target():
            pass
        err = _oom_error()

        def always():
            raise err

        with pytest.raises(RuntimeError) as ei:
            oom.retry_on_oom(always)
        assert ei.value is err

    def test_host_fallback_rung_degrades_operator(self):
        from spark_rapids_tpu.ops.base import Exec, InMemorySourceExec

        schema = (("a", dt.INT64),)
        hb = HostBatch.from_pydict(schema, {"a": [1, 2, 3]})

        class FlakyExec(Exec):
            """Device path exhausts the ladder; host path works."""

            def __init__(self):
                super().__init__(InMemorySourceExec(schema, [[hb]]))

            @property
            def schema(self):
                return schema

            def execute_device(self, ctx, partition):
                def always():
                    raise _oom_error()
                yield oom.retry_on_oom(always)

            def execute_host(self, ctx, partition):
                yield from self.children[0].execute_host(ctx, partition)

        rows = FlakyExec().collect(device=True)
        assert rows == [(1,), (2,), (3,)]
        assert faults.counters().get("hostFallbacks", 0) == 1

    def test_host_fallback_disabled_propagates(self):
        from spark_rapids_tpu.ops.base import Exec, ExecContext, \
            InMemorySourceExec

        schema = (("a", dt.INT64),)
        hb = HostBatch.from_pydict(schema, {"a": [1]})

        class FlakyExec(Exec):
            def __init__(self):
                super().__init__(InMemorySourceExec(schema, [[hb]]))

            @property
            def schema(self):
                return schema

            def execute_device(self, ctx, partition):
                def always():
                    raise _oom_error()
                yield oom.retry_on_oom(always)

            def execute_host(self, ctx, partition):
                yield hb

        ctx = ExecContext(srt.TpuConf(
            {"spark.rapids.sql.oom.hostFallback.enabled": False}))
        with pytest.raises(oom.OomRetryExhausted):
            FlakyExec().collect(ctx, device=True)
        ctx.close()


# ---------------------------------------------------------------------------
# Transient retry: backoff, determinism, budget
# ---------------------------------------------------------------------------

class TestTransientRetry:
    def test_backoff_deterministic_exponential_capped(self):
        d = [oom.backoff_delay_ms(i, 100, 2000, seed=7) for i in range(6)]
        # Deterministic: same inputs, same delays.
        assert d == [oom.backoff_delay_ms(i, 100, 2000, seed=7)
                     for i in range(6)]
        # Jitter stays in [0.5, 1.0) of the exponential envelope…
        for i, x in enumerate(d):
            env = min(100 * 2 ** i, 2000)
            assert env * 0.5 <= x < env
        # …and a different seed moves the jitter.
        assert d != [oom.backoff_delay_ms(i, 100, 2000, seed=8)
                     for i in range(6)]

    def test_retry_budget_exhausts(self):
        s = TpuSession()
        s.set("spark.rapids.sql.test.faults", "transient@download:9")
        s.set("spark.rapids.sql.retry.transientMaxRetries", 2)
        s.set("spark.rapids.sql.retry.backoffMs", 1)
        df = s.create_dataframe({"a": [1, 2, 3]}, [("a", dt.INT64)])
        with pytest.raises(faults.InjectedTransientError):
            df.collect()
        # initial + exactly the budgeted retries ran.
        assert faults.counters().get("retriesAttempted", 0) >= 2

    def test_transient_recovers_within_budget(self):
        s = TpuSession()
        s.set("spark.rapids.sql.test.faults", "transient@download:1")
        s.set("spark.rapids.sql.retry.backoffMs", 1)
        df = s.create_dataframe({"a": [1, 2, 3]}, [("a", dt.INT64)])
        assert sorted(df.collect()) == [(1,), (2,), (3,)]
        assert faults.counters().get("faultsInjected") == 1


# ---------------------------------------------------------------------------
# Wire integrity: CRC32 frames + corruption injection
# ---------------------------------------------------------------------------

class TestWireIntegrity:
    def test_frame_roundtrip_and_detection(self):
        from spark_rapids_tpu.columnar.wire import (
            WireCorruptionError, frame_blob, unframe_blob)
        blob = b"the quick brown batch" * 100
        framed = frame_blob(blob)
        assert unframe_blob(framed) == blob
        # Any single flipped byte — header or payload — is detected.
        for off in (0, 5, 13, 40, len(framed) - 1):
            bad = bytearray(framed)
            bad[off] ^= 0xFF
            with pytest.raises(WireCorruptionError):
                unframe_blob(bytes(bad))
        with pytest.raises(WireCorruptionError):
            unframe_blob(framed[:10])          # truncated
        with pytest.raises(WireCorruptionError):
            unframe_blob(b"XXXX" + framed[4:])  # foreign magic

    def test_injected_disk_corruption_detected_and_recovered(
            self, tmp_path):
        b = _batch(0)
        size = b.device_size_bytes()
        cat = BufferCatalog(device_budget_bytes=int(size * 1.5),
                            host_budget_bytes=int(size * 1.5),
                            spill_dir=str(tmp_path))
        ids = [cat.add_batch(_batch(i)) for i in range(4)]
        tiers = [cat.tier_of(i) for i in ids]
        assert StorageTier.DISK in tiers
        disk_id = ids[tiers.index(StorageTier.DISK)]
        seed = ids.index(disk_id)
        faults.configure("corrupt@wire:1", seed=7)
        restored = cat.acquire_batch(disk_id)
        from spark_rapids_tpu.columnar.host import device_to_host
        want = device_to_host(_batch(seed)).to_pylist()
        assert device_to_host(restored).to_pylist() == want
        assert cat.metrics.get("corruption_detected") == 1
        assert faults.counters().get("corruptionsDetected") == 1
        cat.close()

    def test_persistent_corruption_fails_loudly(self, tmp_path):
        from spark_rapids_tpu.columnar.wire import WireCorruptionError
        b = _batch(0)
        size = b.device_size_bytes()
        cat = BufferCatalog(device_budget_bytes=int(size * 1.5),
                            host_budget_bytes=int(size * 1.5),
                            spill_dir=str(tmp_path))
        ids = [cat.add_batch(_batch(i)) for i in range(4)]
        tiers = [cat.tier_of(i) for i in ids]
        disk_id = ids[tiers.index(StorageTier.DISK)]
        faults.configure("corrupt@wire:5", seed=7)  # every re-read too
        with pytest.raises(WireCorruptionError):
            cat.acquire_batch(disk_id)
        cat.close()


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

class TestFaultRegistry:
    def test_spec_parse(self):
        es = faults.parse_spec(
            "oom@upload:0.05, transient@exchange.flush:2 ,corrupt@wire")
        assert [(e.kind, e.site) for e in es] == [
            ("oom", "upload"), ("transient", "exchange.flush"),
            ("corrupt", "wire")]
        assert es[0].probability == 0.05 and es[0].count is None
        assert es[1].count == 2
        assert es[2].count == 1                # default arg
        for bad in ("oops@upload", "oom@", "oom@x:0", "oom@x:1.5",
                    "justtext"):
            with pytest.raises(faults.FaultParseError):
                faults.parse_spec(bad)
        assert faults.parse_spec("") == []

    def test_count_faults_fire_first_n_hits(self):
        inj = faults.FaultInjector("oom@k:2", seed=1)
        fired = [inj.should_fire("k", ("oom",)) is not None
                 for _ in range(5)]
        assert fired == [True, True, False, False, False]

    def test_probability_faults_deterministic_per_seed(self):
        def run(seed):
            inj = faults.FaultInjector("oom@k:0.3", seed=seed)
            return [inj.should_fire("k", ("oom",)) is not None
                    for _ in range(200)]

        a, b, c = run(7), run(7), run(8)
        assert a == b                      # same seed, same schedule
        assert a != c                      # seed moves it
        assert 20 < sum(a) < 100           # roughly Bernoulli(0.3)

    def test_disarmed_fault_point_is_noop(self):
        faults.configure("")
        faults.fault_point("upload")       # must not raise
        assert faults.corrupt_blob("wire", b"abc") == b"abc"

    def test_fault_point_raises_typed_errors(self):
        faults.configure("oom@a:1,transient@b:1", seed=0)
        with pytest.raises(faults.InjectedOomError):
            faults.fault_point("a")
        with pytest.raises(faults.InjectedTransientError):
            faults.fault_point("b")
        # Markers route into the right recovery machinery.
        faults.configure("oom@a:1,transient@b:1", seed=0)
        try:
            faults.fault_point("a")
        except Exception as e:
            assert oom.is_oom_error(e) and not oom.is_transient_error(e)
        try:
            faults.fault_point("b")
        except Exception as e:
            assert oom.is_transient_error(e) and not oom.is_oom_error(e)
