"""ROLLUP/CUBE (via ExpandExec) and explode (via GenerateExec) through the
public DataFrame API (ref: GpuExpandExec.scala / GpuGenerateExec.scala,
registered in GpuOverrides.scala:1768-1977)."""

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.plan.logical import (
    agg_count, agg_sum, col, explode, explode_outer, posexplode)

from harness import assert_rows_equal


@pytest.fixture
def session():
    return TpuSession()


def dual(frame):
    dev = sorted(frame.collect(), key=repr)
    host = sorted(frame.collect_host(), key=repr)
    assert_rows_equal(dev, host, approx_float=True,
                      msg="device vs host engine")
    return dev


class TestRollupCube:
    @pytest.fixture
    def df(self, session):
        return session.create_dataframe(
            {"a": ["x", "x", "y", None], "b": [1, 2, 1, 1],
             "v": [10.0, 20.0, 30.0, 40.0]},
            [("a", srt.STRING), ("b", srt.INT64), ("v", srt.FLOAT64)],
            num_partitions=2)

    def test_rollup(self, df):
        out = dual(df.rollup("a", "b").agg(
            agg_sum(col("v")).alias("s"), agg_count().alias("c")))
        # 4 data groups + 3 level-1 subtotals + 1 grand total.
        assert len(out) == 8
        assert (None, None, 100.0, 4) in out      # grand total

    def test_cube(self, df):
        out = dual(df.cube("a", "b").agg(agg_sum(col("v")).alias("s")))
        # 4 (a,b) + 3 (a) + 2 (b) + 1 () = 10 groups.
        assert len(out) == 10

    def test_rollup_single_key(self, df):
        out = dual(df.rollup("a").agg(agg_count().alias("c")))
        assert len(out) == 4                      # x, y, NULL, total
        assert (None, 4) in out

    def test_data_null_stays_distinct_from_subtotal(self, df):
        out = dual(df.rollup("a").agg(agg_sum(col("v")).alias("s")))
        nulls = [r for r in out if r[0] is None]
        # Data NULL group (40.0) and grand total (100.0) both present.
        assert sorted(r[1] for r in nulls) == [40.0, 100.0]


class TestExplodeFrontend:
    @pytest.fixture
    def df(self, session):
        return session.create_dataframe(
            {"id": [1, 2], "a": [10, None], "b": [20, 40]},
            [("id", srt.INT64), ("a", srt.INT64), ("b", srt.INT64)],
            num_partitions=2)

    def test_explode(self, df):
        out = dual(df.select("id", explode(col("a"), col("b")).alias("v")))
        assert out == sorted([(1, 10), (1, 20), (2, None), (2, 40)],
                             key=repr)

    def test_posexplode(self, df):
        out = dual(df.select(
            "id", posexplode(col("a"), col("b")).alias("v")))
        assert all(len(r) == 3 for r in out)

    def test_explode_then_agg(self, df):
        out = dual(df.select("id", explode(col("a"), col("b")).alias("v"))
                     .group_by("id").agg(agg_count(col("v")).alias("c")))
        assert sorted(out) == [(1, 2), (2, 1)]
