"""Every registered config key must change behavior somewhere: semaphore
admission, stableSort, hasNans, improvedFloatOps, cast gates,
replaceSortMergeJoin, skipAggPassReductionRatio (VERDICT r3 item 7 — no
decorative keys)."""

import threading
import time

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.plan.logical import agg_sum, col, log_col as log_fn


def make_df(session, **conf):
    for k, v in conf.items():
        session.set(k, v)
    return session.create_dataframe(
        {"k": [1, 2, 1, 2], "v": [1.5, 2.5, 3.5, 4.5],
         "s": ["1.5", "x", "2", None]},
        [("k", srt.INT64), ("v", srt.FLOAT64), ("s", srt.STRING)],
        num_partitions=2)


class TestSemaphore:
    def test_concurrent_tasks_serialize(self):
        """concurrentTpuTasks=1 serializes two concurrent collects
        (GpuSemaphore.scala:74-87 behavior)."""
        from spark_rapids_tpu.memory.stores import TpuSemaphore
        # Direct instance: the process-global one is sized by whichever
        # collect ran first in this test process.
        sem = TpuSemaphore(1)
        windows = []
        lock = threading.Lock()

        def task():
            with sem:
                t0 = time.perf_counter()
                time.sleep(0.05)
                with lock:
                    windows.append((t0, time.perf_counter()))

        threads = [threading.Thread(target=task) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        windows.sort()
        for (s0, e0), (s1, _) in zip(windows, windows[1:]):
            assert s1 >= e0, "collects overlapped under 1 permit"

    def test_collect_goes_through_semaphore(self, monkeypatch):
        """Exec.collect acquires the configured semaphore."""
        from spark_rapids_tpu.memory import stores
        acquired = []
        real = stores.get_tpu_semaphore

        def spy(permits):
            acquired.append(permits)
            return real(permits)

        monkeypatch.setattr(stores, "get_tpu_semaphore", spy)
        s = TpuSession()
        s.set("spark.rapids.sql.concurrentTpuTasks", 3)
        make_df(s).select("k").collect()
        assert 3 in acquired


class TestExprGates:
    def test_improved_float_ops_gate(self):
        s = TpuSession()
        df = make_df(s).select(log_fn(col("v")).alias("l"))
        report = df.explain("NOT_ON_GPU")
        assert "improvedFloatOps" in report
        # Enabling the key clears the fallback.
        s2 = TpuSession()
        s2.set("spark.rapids.sql.improvedFloatOps.enabled", True)
        df2 = make_df(s2).select(log_fn(col("v")).alias("l"))
        assert "improvedFloatOps" not in df2.explain("NOT_ON_GPU")
        # Results agree either way.
        assert df.collect() == df2.collect()

    def test_cast_float_to_string_gate(self):
        s = TpuSession()
        df = make_df(s).select(col("v").cast("string").alias("t"))
        assert "castFloatToString" in df.explain("NOT_ON_GPU")
        s2 = TpuSession()
        s2.set("spark.rapids.sql.castFloatToString.enabled", True)
        df2 = make_df(s2).select(col("v").cast("string").alias("t"))
        assert "castFloatToString" not in df2.explain("NOT_ON_GPU")

    def test_cast_string_to_float_gate(self):
        s = TpuSession()
        df = make_df(s).select(col("s").cast("double").alias("d"))
        assert "castStringToFloat" in df.explain("NOT_ON_GPU")

    def test_replace_sort_merge_join_gate(self):
        s = TpuSession()
        s.set("spark.rapids.sql.replaceSortMergeJoin.enabled", False)
        left = make_df(s)
        right = s.create_dataframe(
            {"k2": [1, 2], "w": [9.0, 8.0]},
            [("k2", srt.INT64), ("w", srt.FLOAT64)])
        j = left.join_on(right, ["k"], ["k2"], strategy="shuffle")
        assert "replaceSortMergeJoin" in j.explain("NOT_ON_GPU")
        # Host fallback still computes the right answer.
        assert sorted(j.collect()) == sorted(j.collect_host())


class TestStableSort:
    def test_stable_sort_preserves_arrival_order(self):
        s = TpuSession()
        s.set("spark.rapids.sql.stableSort.enabled", True)
        df = s.create_dataframe(
            {"k": [1, 1, 1, 1], "i": [0, 1, 2, 3]},
            [("k", srt.INT64), ("i", srt.INT64)])
        out = df.order_by(col("k").asc()).collect()
        assert [r[1] for r in out] == [0, 1, 2, 3]

    def test_unstable_sort_still_sorts(self):
        s = TpuSession()
        s.set("spark.rapids.sql.stableSort.enabled", False)
        df = s.create_dataframe(
            {"k": [3, 1, 2, 1], "i": [0, 1, 2, 3]},
            [("k", srt.INT64), ("i", srt.INT64)])
        out = df.order_by(col("k").asc()).collect()
        assert [r[0] for r in out] == [1, 1, 2, 3]


class TestHasNans:
    def test_hasnans_false_matches_host_on_finite_data(self):
        s = TpuSession()
        s.set("spark.rapids.sql.hasNans", False)
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        df = make_df(s)
        q = df.group_by("k").agg(agg_sum(col("v")).alias("sv"))
        assert sorted(q.collect()) == sorted(q.collect_host())

    def test_hasnans_true_handles_nan(self):
        s = TpuSession()
        s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
        df = s.create_dataframe(
            {"k": [1, 1, 2, 2], "v": [float("nan"), 1.0, 2.0, 3.0]},
            [("k", srt.INT64), ("v", srt.FLOAT64)])
        q = df.group_by("k").agg(agg_sum(col("v")).alias("sv"))
        got = dict(q.collect())
        import math
        assert math.isnan(got[1]) and got[2] == 5.0


class TestFormatAndMemoryGates:
    """Round-5 config additions: per-format read/write gates, per-format
    reader strategies, memory ceiling/reserve, metrics level."""

    def test_parquet_read_gate_falls_back(self, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as papq
        import numpy as np
        from spark_rapids_tpu.api.dataframe import TpuSession
        p = str(tmp_path / "t.parquet")
        papq.write_table(pa.table({"x": np.arange(10,
                                                  dtype=np.int64)}), p)
        s = TpuSession()
        s.set("spark.rapids.sql.format.parquet.read.enabled", False)
        df = s.read.parquet(p)
        report = df._physical().explain()
        assert "parquet scan disabled" in report
        assert sorted(r[0] for r in df.collect()) == list(range(10))

    def test_orc_reader_type_key(self, tmp_path):
        import pyarrow as pa
        import pyarrow.orc as paorc
        import numpy as np
        from spark_rapids_tpu.api.dataframe import TpuSession
        from spark_rapids_tpu.ops.base import ExecContext
        p = str(tmp_path / "t.orc")
        paorc.write_table(pa.table({"x": np.arange(5,
                                                   dtype=np.int64)}), p)
        s = TpuSession()
        s.set("spark.rapids.sql.format.orc.reader.type", "PERFILE")
        df = s.read.orc(p)
        phys = df._physical()
        scan = phys.root
        while scan.children:
            scan = scan.children[0]
        assert scan._reader_type(ExecContext(phys.conf)) == "PERFILE"
        assert df.collect() == [(i,) for i in range(5)]

    def test_write_gate_uses_host_engine(self, tmp_path):
        from spark_rapids_tpu import FLOAT64, INT64
        from spark_rapids_tpu.api.dataframe import TpuSession
        import pyarrow.parquet as papq
        import os
        s = TpuSession()
        s.set("spark.rapids.sql.format.parquet.write.enabled", False)
        df = s.create_dataframe({"x": [1, 2, 3]}, [("x", INT64)])
        out = str(tmp_path / "w")
        stats = df.write.parquet(out)
        assert stats["numOutputRows"] == 3
        files = [f for f in os.listdir(out) if f.endswith(".parquet")]
        rows = sum(papq.read_table(os.path.join(out, f)).num_rows
                   for f in files)
        assert rows == 3

    def test_memory_ceiling_and_reserve(self):
        from spark_rapids_tpu.ops.base import ExecContext, \
            _visible_device_bytes
        from spark_rapids_tpu.config import TpuConf
        visible = _visible_device_bytes()
        conf = TpuConf({
            "spark.rapids.memory.tpu.allocFraction": 0.9,
            "spark.rapids.memory.tpu.maxAllocFraction": 0.5,
            "spark.rapids.memory.tpu.reserve": 1024,
        })
        ctx = ExecContext(conf)
        assert ctx.catalog.device_budget == int(visible * 0.5) - 1024
        ctx.close()

    def test_metrics_level_filters(self):
        from spark_rapids_tpu import FLOAT64, INT64
        from spark_rapids_tpu.api.dataframe import TpuSession
        from spark_rapids_tpu.plan.logical import agg_count
        s = TpuSession()
        df = s.create_dataframe({"x": [1, 2, 3]}, [("x", INT64)]) \
            .agg(agg_count().alias("n"))
        df.collect()
        s.set("spark.rapids.sql.metrics.level", "ESSENTIAL")
        df.collect()    # re-plan under the new conf version
        m = df.metrics()
        allowed = {"numOutputRows", "totalTime"}
        # Audit-trail entries (Recovery/Pipeline/Scheduler@query) are
        # exempt from level filtering by contract — only the
        # per-operator entries must be filtered down.
        audit = {"Recovery@query", "Pipeline@query", "Scheduler@query"}
        assert m and all(set(v) <= allowed for k, v in m.items()
                         if k not in audit)


def test_generated_docs_in_sync():
    """docs/configs.md is the generated config reference (the reference's
    generated docs/configs.md discipline) — regen must be a no-op."""
    import os
    from spark_rapids_tpu.config import generate_docs
    path = os.path.join(os.path.dirname(__file__), "..", "docs",
                        "configs.md")
    assert open(path).read() == generate_docs()
