"""Expression engine tests: device-vs-host parity + oracles.

Modeled on the reference's unit/ expression suites and
SparkQueryCompareTestSuite (SURVEY.md §4): every expression is evaluated via
the jit device path and the numpy host path and must agree exactly.
"""

import math
import struct

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu import exprs as E
from spark_rapids_tpu.exprs.base import BoundReference as Ref, lit

from harness import (check_expr, check_exprs, py_hash_bytes, py_hash_int,
                     py_hash_long, to_signed32)


def make_batch(schema, data):
    return HostBatch.from_pydict(schema, data)


INT_BATCH = make_batch(
    [("a", dt.INT32), ("b", dt.INT32)],
    {"a": [1, -2, 3, None, 2147483647, 0],
     "b": [7, 3, None, 5, 1, 0]})

LONG_BATCH = make_batch(
    [("a", dt.INT64), ("b", dt.INT64)],
    {"a": [10, -7, None, 2**62, -2**62, 123456789],
     "b": [3, 2, 4, None, 3, -10]})

FLOAT_BATCH = make_batch(
    [("x", dt.FLOAT64), ("y", dt.FLOAT64)],
    {"x": [1.5, -2.25, float("nan"), None, float("inf"), -0.0],
     "y": [2.0, 0.0, 1.0, 3.0, None, 4.0]})

STR_BATCH = make_batch(
    [("s", dt.STRING), ("t", dt.STRING)],
    {"s": ["hello", "WORLD", "", None, "héllo", "  pad  "],
     "t": ["he", "ld", "x", "y", None, "pad"]})


class TestArithmetic:
    def test_add(self):
        check_expr(E.Add(Ref(0, dt.INT32), Ref(1, dt.INT32)), INT_BATCH,
                   [8, 1, None, None, -2147483648, 0])  # wraps like the JVM

    def test_subtract_multiply(self):
        check_exprs([E.Subtract(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                     E.Multiply(Ref(0, dt.INT32), Ref(1, dt.INT32))],
                    INT_BATCH,
                    [(-6, 7), (-5, -6), (None, None), (None, None),
                     (2147483646, 2147483647), (0, 0)])

    def test_divide_null_on_zero(self):
        check_expr(E.Divide(Ref(0, dt.INT32), Ref(1, dt.INT32)), INT_BATCH,
                   [1 / 7, -2 / 3, None, None, 2147483647.0, None])

    def test_integral_divide_truncates(self):
        check_expr(E.IntegralDivide(Ref(0, dt.INT64), Ref(1, dt.INT64)),
                   LONG_BATCH, [3, -3, None, None, -1537228672809129301,
                                -12345678])

    def test_remainder_java_sign(self):
        check_expr(E.Remainder(Ref(0, dt.INT64), Ref(1, dt.INT64)),
                   LONG_BATCH, [1, -1, None, None,
                                -(2**62) - (-1537228672809129301) * 3,
                                123456789 % -10 - -10])

    def test_pmod_nonnegative(self):
        b = make_batch([("a", dt.INT32), ("b", dt.INT32)],
                       {"a": [7, -7, 7, -7], "b": [3, 3, -3, -3]})
        check_expr(E.Pmod(Ref(0, dt.INT32), Ref(1, dt.INT32)), b,
                   [1, 2, -2, -1])

    def test_unary(self):
        check_exprs([E.UnaryMinus(Ref(0, dt.INT32)), E.Abs(Ref(0, dt.INT32))],
                    INT_BATCH,
                    [(-1, 1), (2, 2), (-3, 3), (None, None),
                     (-2147483647, 2147483647), (0, 0)])

    def test_least_greatest_skip_nulls(self):
        check_exprs([E.Least(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                     E.Greatest(Ref(0, dt.INT32), Ref(1, dt.INT32))],
                    INT_BATCH,
                    [(1, 7), (-2, 3), (3, 3), (5, 5), (1, 2147483647), (0, 0)])

    def test_bitwise(self):
        check_exprs([E.BitwiseAnd(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                     E.BitwiseOr(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                     E.BitwiseXor(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                     E.BitwiseNot(Ref(0, dt.INT32))],
                    INT_BATCH,
                    [(1 & 7, 1 | 7, 1 ^ 7, ~1),
                     (-2 & 3, -2 | 3, -2 ^ 3, 1),
                     (None, None, None, -4),
                     (None, None, None, None),
                     (1, 2147483647, 2147483646, -2147483648),
                     (0, 0, 0, -1)])

    def test_shifts(self):
        b = make_batch([("a", dt.INT32), ("n", dt.INT32)],
                       {"a": [1, -8, 256, 1], "n": [3, 1, 33, 0]})
        check_exprs([E.ShiftLeft(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                     E.ShiftRight(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                     E.ShiftRightUnsigned(Ref(0, dt.INT32), Ref(1, dt.INT32))],
                    b,
                    [(8, 0, 0), (-16, -4, 2147483644),
                     (512, 128, 128), (1, 1, 1)])  # shift masked to 5 bits


class TestPredicates:
    def test_comparisons_int(self):
        check_exprs([E.EqualTo(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                     E.LessThan(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                     E.GreaterThanOrEqual(Ref(0, dt.INT32), Ref(1, dt.INT32))],
                    INT_BATCH,
                    [(False, True, False), (False, True, False),
                     (None, None, None), (None, None, None),
                     (False, False, True), (True, False, True)])

    def test_nan_semantics(self):
        # Spark: NaN == NaN is true; NaN > everything.
        b = make_batch([("x", dt.FLOAT64), ("y", dt.FLOAT64)],
                       {"x": [float("nan"), float("nan"), 1.0, float("inf")],
                        "y": [float("nan"), 1.0, float("nan"), float("nan")]})
        check_exprs([E.EqualTo(Ref(0, dt.FLOAT64), Ref(1, dt.FLOAT64)),
                     E.GreaterThan(Ref(0, dt.FLOAT64), Ref(1, dt.FLOAT64)),
                     E.LessThan(Ref(0, dt.FLOAT64), Ref(1, dt.FLOAT64))],
                    b,
                    [(True, False, False), (False, True, False),
                     (False, False, True), (False, False, True)])

    def test_string_compare(self):
        b = make_batch([("s", dt.STRING), ("t", dt.STRING)],
                       {"s": ["abc", "abc", "ab", "b", "", None],
                        "t": ["abc", "abd", "abc", "ab", "a", "x"]})
        check_exprs([E.EqualTo(Ref(0, dt.STRING), Ref(1, dt.STRING)),
                     E.LessThan(Ref(0, dt.STRING), Ref(1, dt.STRING))],
                    b,
                    [(True, False), (False, True), (False, True),
                     (False, False), (False, True), (None, None)])

    def test_kleene_and_or(self):
        b = make_batch([("p", dt.BOOL), ("q", dt.BOOL)],
                       {"p": [True, True, True, False, False, None, None],
                        "q": [True, False, None, False, None, True, None]})
        check_exprs([E.And(Ref(0, dt.BOOL), Ref(1, dt.BOOL)),
                     E.Or(Ref(0, dt.BOOL), Ref(1, dt.BOOL))],
                    b,
                    [(True, True), (False, True), (None, True),
                     (False, False), (False, None), (None, True),
                     (None, None)])

    def test_null_checks(self):
        check_exprs([E.IsNull(Ref(0, dt.INT32)), E.IsNotNull(Ref(0, dt.INT32))],
                    INT_BATCH,
                    [(False, True), (False, True), (False, True),
                     (True, False), (False, True), (False, True)])

    def test_equal_null_safe(self):
        check_expr(E.EqualNullSafe(Ref(0, dt.INT32), Ref(1, dt.INT32)),
                   make_batch([("a", dt.INT32), ("b", dt.INT32)],
                              {"a": [1, None, None, 2],
                               "b": [1, None, 3, 4]}),
                   [True, True, False, False])

    def test_in_set(self):
        check_expr(E.InSet(Ref(0, dt.INT32), [1, 3, None]), INT_BATCH,
                   [True, None, True, None, None, None])
        check_expr(E.InSet(Ref(0, dt.STRING), ["hello", "héllo"]), STR_BATCH,
                   [True, False, False, None, True, False])

    def test_isnan(self):
        check_expr(E.IsNan(Ref(0, dt.FLOAT64)), FLOAT_BATCH,
                   [False, False, True, None, False, False])


class TestMath:
    def test_unary_math(self):
        b = make_batch([("x", dt.FLOAT64)],
                       {"x": [4.0, 0.25, None, 1.0]})
        check_exprs([E.Sqrt(Ref(0, dt.FLOAT64)), E.Exp(Ref(0, dt.FLOAT64)),
                     E.Sin(Ref(0, dt.FLOAT64))],
                    b,
                    [(2.0, math.exp(4.0), math.sin(4.0)),
                     (0.5, math.exp(0.25), math.sin(0.25)),
                     (None, None, None),
                     (1.0, math.e, math.sin(1.0))], approx_float=True)

    def test_log_null_domain(self):
        b = make_batch([("x", dt.FLOAT64)], {"x": [math.e, 0.0, -1.0, None]})
        check_expr(E.Log(Ref(0, dt.FLOAT64)), b, [1.0, None, None, None],
                   approx_float=True)

    def test_floor_ceil_long(self):
        b = make_batch([("x", dt.FLOAT64)], {"x": [1.5, -1.5, 2.0, None]})
        check_exprs([E.Floor(Ref(0, dt.FLOAT64)), E.Ceil(Ref(0, dt.FLOAT64))],
                    b, [(1, 2), (-2, -1), (2, 2), (None, None)])

    def test_round_half_up(self):
        b = make_batch([("x", dt.FLOAT64)],
                       {"x": [2.5, -2.5, 1.25, 1.35, None]})
        check_expr(E.Round(Ref(0, dt.FLOAT64), lit(0)), b,
                   [3.0, -3.0, 1.0, 1.0, None], approx_float=True)
        check_expr(E.Round(Ref(0, dt.FLOAT64), lit(1)), b,
                   [2.5, -2.5, 1.3, 1.4, None], approx_float=True)

    def test_pow(self):
        check_expr(E.Pow(lit(2.0), lit(10.0)), INT_BATCH,
                   [1024.0] * 6, approx_float=True)

    def test_inverse_hyperbolics_datagen(self):
        """asinh/acosh/atanh dual-engine parity over adversarial doubles
        (NaN/±inf/±0/huge), with pandas-style numpy oracles (VERDICT
        expression-gap satellite)."""
        from data_gen import DoubleGen, unary_op_batch
        b = unary_op_batch(DoubleGen(), n=96, seed=11)
        for cls in (E.Asinh, E.Acosh, E.Atanh):
            check_expr(cls(Ref(0, dt.FLOAT64)), b, approx_float=True)

    def test_acosh_atanh_domains(self):
        b = make_batch([("x", dt.FLOAT64)],
                       {"x": [1.0, 0.5, -2.0, None, 2.0]})
        got = check_expr(E.Acosh(Ref(0, dt.FLOAT64)), b,
                         approx_float=True)
        assert got[0] == 0.0 and math.isnan(got[1]) \
            and math.isnan(got[2]) and got[3] is None
        b = make_batch([("x", dt.FLOAT64)],
                       {"x": [0.5, 1.0, -1.0, 2.0, None]})
        got = check_expr(E.Atanh(Ref(0, dt.FLOAT64)), b,
                         approx_float=True)
        assert abs(got[0] - math.atanh(0.5)) < 1e-12
        assert got[1] == math.inf and got[2] == -math.inf
        assert math.isnan(got[3]) and got[4] is None

    def test_logarithm_arbitrary_base(self):
        """log(base, x): NULL outside the domain (base <= 0, base == 1,
        x <= 0), exact ratios inside it; fuzzed dual-engine parity."""
        b = make_batch([("x", dt.FLOAT64)],
                       {"x": [8.0, 0.5, -1.0, 0.0, None]})
        check_expr(E.Logarithm(lit(2.0), Ref(0, dt.FLOAT64)), b,
                   [3.0, -1.0, None, None, None], approx_float=True)
        b = make_batch([("b", dt.FLOAT64), ("x", dt.FLOAT64)],
                       {"b": [10.0, 1.0, -2.0, 0.5, None],
                        "x": [100.0, 5.0, 5.0, 4.0, 2.0]})
        check_expr(E.Logarithm(Ref(0, dt.FLOAT64), Ref(1, dt.FLOAT64)),
                   b, [2.0, None, None, -2.0, None], approx_float=True)
        from data_gen import DoubleGen, binary_op_batch
        fuzz = binary_op_batch(DoubleGen(), DoubleGen(), n=96, seed=12)
        check_expr(E.Logarithm(Ref(0, dt.FLOAT64), Ref(1, dt.FLOAT64)),
                   fuzz, approx_float=True)


class TestAtLeastNNonNulls:
    def test_basic_and_nan_counts_as_null(self):
        b = make_batch([("a", dt.FLOAT64), ("b", dt.INT64),
                        ("c", dt.STRING)],
                       {"a": [1.0, None, float("nan"), 2.0],
                        "b": [1, None, 3, None],
                        "c": ["x", "y", None, None]})
        exprs = [Ref(0, dt.FLOAT64), Ref(1, dt.INT64), Ref(2, dt.STRING)]
        check_expr(E.AtLeastNNonNulls(2, *exprs), b,
                   [True, False, False, False])
        check_expr(E.AtLeastNNonNulls(1, *exprs), b,
                   [True, True, True, True])
        check_expr(E.AtLeastNNonNulls(0, *exprs), b, [True] * 4)
        check_expr(E.AtLeastNNonNulls(4, *exprs), b, [False] * 4)

    def test_datagen_parity(self):
        from data_gen import (DoubleGen, IntegerGen, StringGen,
                              gen_batch)
        b = gen_batch([("a", DoubleGen()), ("b", IntegerGen()),
                       ("c", StringGen())], 96, seed=13)
        check_expr(E.AtLeastNNonNulls(
            2, Ref(0, dt.FLOAT64), Ref(1, dt.INT32),
            Ref(2, dt.STRING)), b)


class TestConditional:
    def test_if_null_pred_takes_else(self):
        b = make_batch([("p", dt.BOOL), ("a", dt.INT32), ("b", dt.INT32)],
                       {"p": [True, False, None], "a": [1, 2, 3],
                        "b": [10, 20, 30]})
        check_expr(E.If(Ref(0, dt.BOOL), Ref(1, dt.INT32), Ref(2, dt.INT32)),
                   b, [1, 20, 30])

    def test_case_when(self):
        b = make_batch([("x", dt.INT32)], {"x": [1, 5, 15, None]})
        expr = E.CaseWhen(
            [(E.LessThan(Ref(0, dt.INT32), lit(3)), lit(100)),
             (E.LessThan(Ref(0, dt.INT32), lit(10)), lit(200))],
            lit(300))
        check_expr(expr, b, [100, 200, 300, 300])

    def test_case_when_no_else(self):
        b = make_batch([("x", dt.INT32)], {"x": [1, 15]})
        expr = E.CaseWhen([(E.LessThan(Ref(0, dt.INT32), lit(3)), lit(100))])
        check_expr(expr, b, [100, None])

    def test_coalesce(self):
        b = make_batch([("a", dt.INT32), ("b", dt.INT32)],
                       {"a": [None, 2, None], "b": [1, 5, None]})
        check_expr(E.Coalesce(Ref(0, dt.INT32), Ref(1, dt.INT32), lit(9)),
                   b, [1, 2, 9])

    def test_coalesce_strings(self):
        b = make_batch([("a", dt.STRING), ("b", dt.STRING)],
                       {"a": [None, "xy", None], "b": ["abc", "q", None]})
        check_expr(E.Coalesce(Ref(0, dt.STRING), Ref(1, dt.STRING)),
                   b, ["abc", "xy", None])

    def test_nanvl(self):
        check_expr(E.NaNvl(Ref(0, dt.FLOAT64), Ref(1, dt.FLOAT64)),
                   FLOAT_BATCH, [1.5, -2.25, 1.0, None, float("inf"), -0.0])


class TestCast:
    def test_int_widening_narrowing(self):
        b = make_batch([("x", dt.INT64)],
                       {"x": [1, 300, -129, None, 2**40]})
        check_expr(E.Cast(Ref(0, dt.INT64), dt.INT8), b,
                   [1, 44, 127, None, 0])  # JVM wrap-around

    def test_float_to_int_truncate(self):
        b = make_batch([("x", dt.FLOAT64)],
                       {"x": [1.9, -1.9, float("nan"), 1e20, None]})
        check_expr(E.Cast(Ref(0, dt.FLOAT64), dt.INT64), b,
                   [1, -1, 0, 9223372036854775807, None])

    def test_bool_casts(self):
        b = make_batch([("x", dt.INT32)], {"x": [0, 1, -5, None]})
        check_expr(E.Cast(Ref(0, dt.INT32), dt.BOOL), b,
                   [False, True, True, None])

    def test_int_to_string(self):
        b = make_batch([("x", dt.INT32)], {"x": [0, -42, 2147483647, None]})
        check_expr(E.Cast(Ref(0, dt.INT32), dt.STRING), b,
                   ["0", "-42", "2147483647", None])

    def test_string_to_int_invalid_null(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["42", " 7 ", "abc", "", None, "99999999999"]})
        check_expr(E.Cast(Ref(0, dt.STRING), dt.INT32), b,
                   [42, 7, None, None, None, None])

    def test_string_to_double(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["1.5", "NaN", "-Infinity", "x", None]})
        out = check_expr(E.Cast(Ref(0, dt.STRING), dt.FLOAT64), b)
        assert out[0] == 1.5 and math.isnan(out[1])
        assert out[2] == float("-inf") and out[3] is None and out[4] is None

    def test_timestamp_date_roundtrip(self):
        b = make_batch([("t", dt.TIMESTAMP)],
                       {"t": [0, 86400_000_000 + 3600_000_000,
                              -1, None]})
        check_expr(E.Cast(Ref(0, dt.TIMESTAMP), dt.DATE), b,
                   [0, 1, -1, None])
        b2 = make_batch([("d", dt.DATE)], {"d": [0, 1, -1, None]})
        check_expr(E.Cast(Ref(0, dt.DATE), dt.TIMESTAMP), b2,
                   [0, 86400_000_000, -86400_000_000, None])

    def test_string_to_date(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["1970-01-01", "1970-01-02", "1969-12-31",
                              "2020-02-29", "bad", None]})
        check_expr(E.Cast(Ref(0, dt.STRING), dt.DATE), b,
                   [0, 1, -1, 18321, None, None])


class TestDatetime:
    DATES = make_batch(
        [("d", dt.DATE)],
        # 1970-01-01, 2000-02-29, 2020-12-31, 1969-12-31, null
        {"d": [0, 11016, 18627, -1, None]})

    def test_ymd(self):
        check_exprs([E.Year(Ref(0, dt.DATE)), E.Month(Ref(0, dt.DATE)),
                     E.DayOfMonth(Ref(0, dt.DATE))],
                    self.DATES,
                    [(1970, 1, 1), (2000, 2, 29), (2020, 12, 31),
                     (1969, 12, 31), (None, None, None)])

    def test_dow_doy_quarter(self):
        check_exprs([E.DayOfWeek(Ref(0, dt.DATE)),
                     E.DayOfYear(Ref(0, dt.DATE)),
                     E.Quarter(Ref(0, dt.DATE))],
                    self.DATES,
                    # 1970-01-01 was a Thursday -> Spark dayofweek=5
                    [(5, 1, 1), (3, 60, 1), (5, 366, 4), (4, 365, 4),
                     (None, None, None)])

    def test_last_day_add_months(self):
        check_expr(E.LastDay(Ref(0, dt.DATE)), self.DATES,
                   [30, 11016, 18627, 30 - 31, None])
        b = make_batch([("d", dt.DATE), ("n", dt.INT32)],
                       {"d": [0, 11016], "n": [1, 12]})
        # 1970-01-01 +1mo = 1970-02-01 (31); 2000-02-29 +12mo = 2001-02-28
        check_expr(E.AddMonths(Ref(0, dt.DATE), Ref(1, dt.INT32)), b,
                   [31, 11016 + 365])

    def test_time_parts(self):
        b = make_batch([("t", dt.TIMESTAMP)],
                       {"t": [3600_000_000 * 5 + 60_000_000 * 7 + 9_000_000,
                              -1_000_000, None]})
        check_exprs([E.Hour(Ref(0, dt.TIMESTAMP)),
                     E.Minute(Ref(0, dt.TIMESTAMP)),
                     E.Second(Ref(0, dt.TIMESTAMP))],
                    b, [(5, 7, 9), (23, 59, 59), (None, None, None)])

    def test_date_arith(self):
        b = make_batch([("d", dt.DATE), ("n", dt.INT32)],
                       {"d": [100, 0, None], "n": [5, -3, 1]})
        check_exprs([E.DateAdd(Ref(0, dt.DATE), Ref(1, dt.INT32)),
                     E.DateSub(Ref(0, dt.DATE), Ref(1, dt.INT32))],
                    b, [(105, 95), (-3, 3), (None, None)])


class TestStrings:
    def test_upper_lower(self):
        check_exprs([E.Upper(Ref(0, dt.STRING)), E.Lower(Ref(0, dt.STRING))],
                    STR_BATCH,
                    [("HELLO", "hello"), ("WORLD", "world"), ("", ""),
                     (None, None), ("HéLLO", "héllo"),
                     ("  PAD  ", "  pad  ")])

    def test_length_chars(self):
        check_expr(E.Length(Ref(0, dt.STRING)), STR_BATCH,
                   [5, 5, 0, None, 5, 7])  # héllo = 5 chars, 6 bytes

    def test_substring(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["hello", "héllo", "ab", None]})
        check_expr(E.Substring(Ref(0, dt.STRING), lit(2), lit(3)), b,
                   ["ell", "éll", "b", None])
        # Spark: start = len + pos; when that is < 0 the requested length is
        # consumed from the virtual negative start ('ab',-3,2 -> 'a').
        check_expr(E.Substring(Ref(0, dt.STRING), lit(-3), lit(2)), b,
                   ["ll", "ll", "a", None])
        check_expr(E.Substring(Ref(0, dt.STRING), lit(0), lit(2)), b,
                   ["he", "hé", "ab", None])

    def test_contains_starts_ends(self):
        check_exprs([E.Contains(Ref(0, dt.STRING), lit("ll")),
                     E.StartsWith(Ref(0, dt.STRING), lit("he")),
                     E.EndsWith(Ref(0, dt.STRING), lit("lo"))],
                    STR_BATCH,
                    [(True, True, True), (False, False, False),
                     (False, False, False), (None, None, None),
                     (True, False, True), (False, False, False)])

    def test_locate(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["hello", "lol", "xyz", None]})
        check_expr(E.StringLocate(lit("l"), Ref(0, dt.STRING), lit(1)), b,
                   [3, 1, 0, None])
        check_expr(E.StringLocate(lit("l"), Ref(0, dt.STRING), lit(4)), b,
                   [4, 0, 0, None])

    def test_concat(self):
        check_expr(E.ConcatStrings(Ref(0, dt.STRING), lit("_"),
                                   Ref(1, dt.STRING)),
                   STR_BATCH,
                   ["hello_he", "WORLD_ld", "_x", None, None, "  pad  _pad"])

    def test_trim(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["  hi  ", "hi", "   ", "", None]})
        check_exprs([E.StringTrim(Ref(0, dt.STRING)),
                     E.StringTrimLeft(Ref(0, dt.STRING)),
                     E.StringTrimRight(Ref(0, dt.STRING))],
                    b,
                    [("hi", "hi  ", "  hi"), ("hi", "hi", "hi"),
                     ("", "", ""), ("", "", ""), (None, None, None)])

    def test_replace(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["banana", "abc", None]})
        check_expr(E.StringReplace(Ref(0, dt.STRING), "an", "AN"), b,
                   ["bANANa", "abc", None])

    def test_regexp_replace(self):
        b = make_batch([("s", dt.STRING)], {"s": ["a1b22c", None]})
        check_expr(E.RegExpReplace(Ref(0, dt.STRING), r"\d+", "#"), b,
                   ["a#b#c", None])

    def test_like(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["hello", "help", "yell", "hl", None]})
        check_expr(E.Like(Ref(0, dt.STRING), "hel%"), b,
                   [True, True, False, False, None])
        check_expr(E.Like(Ref(0, dt.STRING), "%ell%"), b,
                   [True, False, True, False, None])
        check_expr(E.Like(Ref(0, dt.STRING), "h_l%"), b,
                   [True, True, False, False, None])
        check_expr(E.Like(Ref(0, dt.STRING), "hello"), b,
                   [True, False, False, False, None])
        check_expr(E.Like(Ref(0, dt.STRING), "h%l%o"), b,
                   [True, False, False, False, None])


class TestMurmur3:
    def test_hash_int_vs_oracle(self):
        vals = [0, 1, -1, 42, 2147483647, -2147483648]
        b = make_batch([("x", dt.INT32)], {"x": vals})
        expected = [to_signed32(py_hash_int(v & 0xFFFFFFFF, 42))
                    for v in vals]
        check_expr(E.Murmur3Hash([Ref(0, dt.INT32)]), b, expected)

    def test_hash_long_vs_oracle(self):
        vals = [0, 1, -1, 2**62, -2**63]
        b = make_batch([("x", dt.INT64)], {"x": vals})
        expected = [to_signed32(py_hash_long(v, 42)) for v in vals]
        check_expr(E.Murmur3Hash([Ref(0, dt.INT64)]), b, expected)

    def test_hash_string_vs_oracle(self):
        vals = ["", "a", "ab", "abc", "abcd", "abcde", "hello world! longer",
                "héllo"]
        b = make_batch([("s", dt.STRING)], {"s": vals})
        expected = [to_signed32(py_hash_bytes(v.encode(), 42)) for v in vals]
        check_expr(E.Murmur3Hash([Ref(0, dt.STRING)]), b, expected)

    def test_hash_double_and_chain(self):
        b = make_batch([("x", dt.FLOAT64), ("y", dt.INT32)],
                       {"x": [1.5, float("nan"), None], "y": [7, 8, 9]})
        exp = []
        for x, y in [(1.5, 7), (float("nan"), 8), (None, 9)]:
            seed = 42
            if x is not None:
                bits = struct.unpack("<q", struct.pack("<d", x))[0] \
                    if not math.isnan(x) else 0x7FF8000000000000
                seed = py_hash_long(bits, seed)
            exp.append(to_signed32(py_hash_int(y, seed)))
        check_expr(E.Murmur3Hash([Ref(0, dt.FLOAT64), Ref(1, dt.INT32)]),
                   b, exp)

    def test_null_passes_seed(self):
        b = make_batch([("x", dt.INT32)], {"x": [None]})
        check_expr(E.Murmur3Hash([Ref(0, dt.INT32)]), b, [42])


class TestReviewRegressions:
    """Regressions for the round-1 code-review findings."""

    def test_substr_int_max_len(self):
        # substr(s, pos) desugars to len = Int.MaxValue; must not wrap.
        b = make_batch([("s", dt.STRING)], {"s": ["hello", "ab", None]})
        check_expr(E.Substring(Ref(0, dt.STRING), lit(2), lit(2147483647)),
                   b, ["ello", "b", None])

    def test_float_to_int_saturates(self):
        b = make_batch([("x", dt.FLOAT64)],
                       {"x": [1e10, -1e10, 1e300, None]})
        # d2i saturation at Int range...
        check_expr(E.Cast(Ref(0, dt.FLOAT64), dt.INT32), b,
                   [2147483647, -2147483648, 2147483647, None])
        # ...then wrap-narrow for byte (Scala x.toInt.toByte).
        check_expr(E.Cast(Ref(0, dt.FLOAT64), dt.INT8), b,
                   [-1, 0, -1, None])

    def test_float_to_timestamp_nan_inf_null(self):
        b = make_batch([("x", dt.FLOAT64)],
                       {"x": [1.5, float("nan"), float("inf"), None]})
        check_expr(E.Cast(Ref(0, dt.FLOAT64), dt.TIMESTAMP), b,
                   [1500000, None, None, None])

    def test_least_nan_is_greatest(self):
        b = make_batch([("x", dt.FLOAT64), ("y", dt.FLOAT64)],
                       {"x": [float("nan"), float("nan"), 1.0,
                              float("inf")],
                        "y": [1.0, float("nan"), 2.0, float("nan")]})
        out = check_expr(E.Least(Ref(0, dt.FLOAT64), Ref(1, dt.FLOAT64)), b)
        assert out[0] == 1.0 and math.isnan(out[1]) and out[2] == 1.0
        assert out[3] == float("inf")
        out = check_expr(E.Greatest(Ref(0, dt.FLOAT64), Ref(1, dt.FLOAT64)),
                         b)
        assert math.isnan(out[0]) and math.isnan(out[1]) and out[2] == 2.0
        assert math.isnan(out[3])

    def test_locate_start_below_one(self):
        b = make_batch([("s", dt.STRING)], {"s": ["hello"]})
        check_expr(E.StringLocate(lit("l"), Ref(0, dt.STRING), lit(0)), b,
                   [0])
        check_expr(E.StringLocate(lit("l"), Ref(0, dt.STRING), lit(-2)), b,
                   [0])

    def test_coalesce_wider_first_string(self):
        # Accumulator narrower than a later (earlier-arg) wider literal.
        b = make_batch([("s", dt.STRING)], {"s": [None, "zz"]})
        check_expr(E.Coalesce(lit("a-very-long-literal-string"),
                              Ref(0, dt.STRING), lit("bb")),
                   b, ["a-very-long-literal-string"] * 2)
        check_expr(E.Coalesce(Ref(0, dt.STRING),
                              lit("a-very-long-literal-string")),
                   b, ["a-very-long-literal-string", "zz"])

    def test_case_when_wide_branch_strings(self):
        b = make_batch([("x", dt.INT32)], {"x": [1, 9]})
        expr = E.CaseWhen(
            [(E.LessThan(Ref(0, dt.INT32), lit(5)),
              lit("quite-a-long-result-string"))], lit("s"))
        check_expr(expr, b, ["quite-a-long-result-string", "s"])

    def test_cast_string_identity(self):
        b = make_batch([("s", dt.STRING)], {"s": ["abc", None]})
        check_expr(E.Cast(Ref(0, dt.STRING), dt.STRING), b, ["abc", None])

    def test_round_bigint_exact(self):
        v = 2**60 + 1
        b = make_batch([("x", dt.INT64)], {"x": [v, -v, 125, None]})
        check_expr(E.Round(Ref(0, dt.INT64), 0), b, [v, -v, 125, None])
        check_expr(E.Round(Ref(0, dt.INT64), -1), b,
                   [1152921504606846980, -1152921504606846980, 130, None])

    def test_host_column_none_string_entries(self):
        # HostColumn permits None entries for nulls; kernels must not crash.
        import numpy as np
        from spark_rapids_tpu.columnar.host import HostColumn
        data = np.empty(2, dtype=object)
        data[0] = b"ok"
        data[1] = None
        hc = HostColumn(dt.STRING, data, np.array([True, False]))
        hb = HostBatch(("s",), [hc])
        check_expr(E.Upper(Ref(0, dt.STRING)), hb, ["OK", None])


class TestNewStringExprs:
    """Round-3 expression breadth (GpuOverrides.scala:537-1667 surface)."""

    def test_concat_ws_skips_nulls(self):
        b = make_batch([("s", dt.STRING), ("t", dt.STRING)],
                       {"s": ["a", None, "c", None],
                        "t": ["x", "y", None, None]})
        check_expr(E.ConcatWs("-", Ref(0, dt.STRING), Ref(1, dt.STRING)),
                   b, ["a-x", "y", "c", ""])

    def test_concat_ws_multi(self):
        b = make_batch(
            [("a", dt.STRING), ("b", dt.STRING), ("c", dt.STRING)],
            {"a": ["1", "1", None], "b": [None, "2", None],
             "c": ["3", "3", None]})
        check_expr(E.ConcatWs(", ", Ref(0, dt.STRING), Ref(1, dt.STRING),
                              Ref(2, dt.STRING)),
                   b, ["1, 3", "1, 2, 3", ""])

    def test_repeat(self):
        b = make_batch([("s", dt.STRING)], {"s": ["ab", "", None, "x"]})
        check_expr(E.StringRepeat(Ref(0, dt.STRING), 3), b,
                   ["ababab", "", None, "xxx"])

    def test_reverse_utf8(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["abc", "", None, "héllo", "abé"]})
        check_expr(E.StringReverse(Ref(0, dt.STRING)), b,
                   ["cba", "", None, "olléh", "éba"])

    def test_initcap(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["hello world", "fOO bAR", "", None, "a b c"]})
        check_expr(E.InitCap(Ref(0, dt.STRING)), b,
                   ["Hello World", "Foo Bar", "", None, "A B C"])

    def test_regexp_extract(self):
        b = make_batch([("s", dt.STRING)],
                       {"s": ["100-200", "foo", None, "7-8"]})
        check_expr(E.RegExpExtract(Ref(0, dt.STRING), r"(\d+)-(\d+)", 1),
                   b, ["100", "", None, "7"])
        check_expr(E.RegExpExtract(Ref(0, dt.STRING), r"(\d+)-(\d+)", 2),
                   b, ["200", "", None, "8"])

    def test_translate(self):
        b = make_batch([("s", dt.STRING)], {"s": ["abcba", None, "xyz"]})
        check_expr(E.Translate(Ref(0, dt.STRING), "abx", "AB"), b,
                   ["ABcBA", None, "yz"])

    def test_lpad_rpad(self):
        b = make_batch([("s", dt.STRING)], {"s": ["hi", "longer", None]})
        check_expr(E.StringLPad(Ref(0, dt.STRING), 5, "*"), b,
                   ["***hi", "longe", None])
        check_expr(E.StringRPad(Ref(0, dt.STRING), 5, "*"), b,
                   ["hi***", "longe", None])

    def test_lpad_nonpositive_length_is_empty(self):
        # Spark: lpad/rpad with len <= 0 returns '' (not a tail slice).
        b = make_batch([("s", dt.STRING)], {"s": ["hello", "", None]})
        check_expr(E.StringLPad(Ref(0, dt.STRING), -1, "*"), b,
                   ["", "", None])
        check_expr(E.StringRPad(Ref(0, dt.STRING), 0, "*"), b,
                   ["", "", None])

    def test_concat_ws_no_columns(self):
        b = make_batch([("s", dt.STRING)], {"s": ["a", "b"]})
        check_expr(E.ConcatWs("-"), b, ["", ""])


class TestBRound:
    def test_bround_half_even_float(self):
        b = make_batch([("x", dt.FLOAT64)],
                       {"x": [2.5, 3.5, -2.5, 1.25, None]})
        check_expr(E.BRound(Ref(0, dt.FLOAT64), 0), b,
                   [2.0, 4.0, -2.0, 1.0, None])
        check_expr(E.BRound(Ref(0, dt.FLOAT64), 1), b,
                   [2.5, 3.5, -2.5, 1.2, None], approx_float=True)

    def test_bround_int_negative_scale(self):
        b = make_batch([("x", dt.INT64)],
                       {"x": [25, 35, -25, -35, 24, 26, None]})
        check_expr(E.BRound(Ref(0, dt.INT64), -1), b,
                   [20, 40, -20, -40, 20, 30, None])


class TestTruncDate:
    def test_trunc_year_month_quarter_week(self):
        import datetime as pydt
        epoch = pydt.date(1970, 1, 1)
        days = lambda y, m, d: (pydt.date(y, m, d) - epoch).days
        b = make_batch([("d", dt.DATE)],
                       {"d": [days(2020, 7, 17), days(2019, 2, 28), None]})
        check_expr(E.TruncDate(Ref(0, dt.DATE), "year"), b,
                   [days(2020, 1, 1), days(2019, 1, 1), None])
        check_expr(E.TruncDate(Ref(0, dt.DATE), "month"), b,
                   [days(2020, 7, 1), days(2019, 2, 1), None])
        check_expr(E.TruncDate(Ref(0, dt.DATE), "quarter"), b,
                   [days(2020, 7, 1), days(2019, 1, 1), None])
        # 2020-07-17 is a Friday -> Monday 2020-07-13.
        check_expr(E.TruncDate(Ref(0, dt.DATE), "week"), b,
                   [days(2020, 7, 13), days(2019, 2, 25), None])

    def test_trunc_bad_format_is_null(self):
        b = make_batch([("d", dt.DATE)], {"d": [1000, None]})
        check_expr(E.TruncDate(Ref(0, dt.DATE), "bogus"), b, [None, None])


class TestSplitSubstringIndex:
    """StringSplit (element-access form) + SubstringIndex parity against
    a pure-python oracle over data_gen-generated strings (ROADMAP item 5
    expression-gap slice) — the split(...).getItem(i) and
    substring_index shapes that previously forced a host fallback."""

    @staticmethod
    def _py_split(s, d, i):
        if s is None:
            return None
        parts = s.split(d)
        return parts[i] if 0 <= i < len(parts) else None

    @staticmethod
    def _py_ssi(s, d, c):
        if s is None:
            return None
        if c == 0:
            return ""
        parts = s.split(d)
        if c > 0:
            return d.join(parts[:c]) if len(parts) > c else s
        k = -c
        return d.join(parts[-k:]) if len(parts) > k else s

    def _gen_strings(self, delim, n=80):
        """data_gen strings joined with the delimiter so generated rows
        carry 0..3 occurrences (plus the generator's own specials)."""
        from data_gen import StringGen
        rng = np.random.default_rng(99)
        gen = StringGen(nullable=True)
        # Cap piece width: the byte-matrix width drives kernel cost and
        # the parity property is width-independent.
        pieces = [None if p is None else p[:16]
                  for p in gen.gen(rng, n * 2)]
        out = []
        for i in range(n):
            k = int(rng.integers(0, 4))
            parts = [pieces[(i * 3 + j) % len(pieces)] or ""
                     for j in range(k + 1)]
            if pieces[i * 2 % len(pieces)] is None and k == 0:
                out.append(None)
            else:
                out.append(delim.join(parts))
        return out

    @pytest.mark.parametrize("delim", [",", "ab"])
    def test_split_parity(self, delim):
        vals = self._gen_strings(delim)
        b = make_batch([("s", dt.STRING)], {"s": vals})
        for i in (0, 1, 5, -1):
            check_expr(E.StringSplit(Ref(0, dt.STRING), delim, i), b,
                       [self._py_split(v, delim, i) if i >= 0 else None
                        for v in vals])

    @pytest.mark.parametrize("delim", [",", "ab"])
    def test_substring_index_parity(self, delim):
        vals = self._gen_strings(delim)
        b = make_batch([("s", dt.STRING)], {"s": vals})
        for c in (1, 2, -1, 0):
            check_expr(E.SubstringIndex(Ref(0, dt.STRING), delim, c), b,
                       [self._py_ssi(v, delim, c) for v in vals])

    def test_overlapping_multibyte_delimiter(self):
        vals = ["aaa", "aabaa", "aaaa", "xaay", None, "", "aa"]
        b = make_batch([("s", dt.STRING)], {"s": vals})
        for i in (0, 1, 2):
            check_expr(E.StringSplit(Ref(0, dt.STRING), "aa", i), b,
                       [self._py_split(v, "aa", i) for v in vals])
        for c in (1, -1):
            check_expr(E.SubstringIndex(Ref(0, dt.STRING), "aa", c), b,
                       [self._py_ssi(v, "aa", c) for v in vals])

    def test_empty_delimiter_rejected(self):
        with pytest.raises(ValueError):
            E.StringSplit(Ref(0, dt.STRING), "", 0)
        with pytest.raises(ValueError):
            E.SubstringIndex(Ref(0, dt.STRING), "", 1)

    def test_frontend_lowering(self):
        from spark_rapids_tpu.api.dataframe import TpuSession
        from spark_rapids_tpu.plan.logical import (
            col, split, substring_index)
        s = TpuSession()
        df = s.create_dataframe(
            {"s": ["a.b.c", "x", None, "p.q"]}, [("s", dt.STRING)])
        out = df.select(
            split(col("s"), ".", 1).alias("second"),
            substring_index(col("s"), ".", 2).alias("prefix")).collect()
        assert out == [("b", "a.b"), (None, "x"), (None, None),
                       ("q", "p.q")]


class TestMd5:
    """Md5 (VERDICT row 8 expression-gap remainder): the vectorized
    device/host MD5 against hashlib over data_gen strings, including
    every padding boundary (55/56/64-byte chunk edges)."""

    @staticmethod
    def _oracle(vals):
        import hashlib
        return [None if v is None
                else hashlib.md5(v.encode("utf-8")).hexdigest()
                for v in vals]

    def test_md5_padding_boundaries(self):
        vals = ["", "abc", "a" * 54, "b" * 55, "c" * 56, "d" * 63,
                "e" * 64, "f" * 65, None, "g" * 119, "h" * 120]
        b = make_batch([("s", dt.STRING)], {"s": vals})
        check_expr(E.Md5(Ref(0, dt.STRING)), b, self._oracle(vals))

    def test_md5_data_gen_parity(self):
        from data_gen import StringGen
        rng = np.random.default_rng(42)
        vals = StringGen(nullable=True).gen(rng, 96)
        b = make_batch([("s", dt.STRING)], {"s": vals})
        check_expr(E.Md5(Ref(0, dt.STRING)), b, self._oracle(vals))

    def test_md5_dataframe_api(self):
        from spark_rapids_tpu.api.dataframe import TpuSession
        from spark_rapids_tpu.plan.logical import col, md5
        s = TpuSession()
        df = s.create_dataframe({"s": ["hello", None, ""]},
                                [("s", dt.STRING)])
        out = df.select(md5(col("s")).alias("h")).collect()
        import hashlib
        assert out == [(hashlib.md5(b"hello").hexdigest(),), (None,),
                       (hashlib.md5(b"").hexdigest(),)]
