"""Pipelined partition executor (ISSUE 4): bit-identity vs the serial
path, deterministic ordering, recovery parity, and the overlap counters.

The contract under test: ``spark.rapids.sql.pipeline.*`` may only change
WHEN host work happens, never WHAT is computed — results (including
partition order) are bit-identical to the serial dispatch for every
prefetch depth, under seeded fault schedules, and with the watchdog
armed; ``SRT_PIPELINE=0`` / ``pipeline.enabled=false`` restore the
serial path exactly (no pipeline metrics entry, no threads).
"""

import os
import threading

import pytest

from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.parallel import pipeline as PL

QUERIES = ["q1", "q3", "q5"]

# Under the serial CI matrix entry the overlap machinery is (correctly)
# inert; only the counter-presence assertions are meaningless then —
# bit-identity and recovery tests run in both modes.
requires_pipeline = pytest.mark.skipif(
    os.environ.get("SRT_PIPELINE", "") == "0",
    reason="pipeline disabled via SRT_PIPELINE=0")


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_pipeline"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


def _session(chaos: str = "", pipeline: bool = True,
             prefetch: int = 2, **extra):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.pipeline.enabled", pipeline)
    s.set("spark.rapids.sql.pipeline.prefetchPartitions", prefetch)
    s.set("spark.rapids.sql.test.faults", chaos)
    s.set("spark.rapids.sql.test.faults.seed", 7)
    s.set("spark.rapids.sql.retry.backoffMs", 1)
    if chaos:
        # The device scan cache would serve decoded units and skip the
        # host decode (and with it the ``scan`` fault site) entirely.
        s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    for k, v in extra.items():
        s.set(k, v)
    return s


@pytest.fixture(scope="module")
def baselines(data_dir):
    """Serial-path device results (the bit-identity oracle)."""
    return {qn: tpch.QUERIES[qn](_session(pipeline=False), data_dir)
            .collect() for qn in QUERIES}


# ---------------------------------------------------------------------------
# Bit-identity + deterministic ordering
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("prefetch", [1, 2, 8])
@pytest.mark.parametrize("qname", QUERIES)
def test_bit_identical_vs_serial(qname, prefetch, baselines, data_dir):
    df = tpch.QUERIES[qname](_session(prefetch=prefetch), data_dir)
    got = df.collect()
    assert got == baselines[qname], (
        f"{qname} @ prefetchPartitions={prefetch} diverged from serial")


@pytest.mark.parametrize("prefetch", [1, 2, 8])
def test_deterministic_partition_ordering(prefetch, data_dir):
    """A bare multi-partition scan+filter (no agg/sort to mask ordering):
    collect order must equal the serial partition-order concatenation."""
    import glob
    from spark_rapids_tpu.plan.logical import col
    paths = sorted(glob.glob(f"{data_dir}/lineitem/*.parquet"))
    want = None
    for pipeline in (False, True):
        s = _session(pipeline=pipeline, prefetch=prefetch)
        df = s.read.parquet(*paths) \
            .filter(col("l_quantity") < 10) \
            .select("l_orderkey", "l_linenumber", "l_quantity")
        rows = df.collect()
        if want is None:
            want = rows
        else:
            assert rows == want, (
                f"ordering diverged at prefetchPartitions={prefetch}")
    assert want, "scan returned no rows — fixture too small"


# ---------------------------------------------------------------------------
# Serial escape hatches
# ---------------------------------------------------------------------------

def test_conf_off_restores_serial(data_dir, baselines):
    df = tpch.QUERIES["q1"](_session(pipeline=False), data_dir)
    got = df.collect()
    assert got == baselines["q1"]
    assert "Pipeline@query" not in df.metrics(), \
        "serial path must not open a pipeline"


def test_env_srt_pipeline_restores_serial(data_dir, baselines,
                                          monkeypatch):
    monkeypatch.setenv("SRT_PIPELINE", "0")
    df = tpch.QUERIES["q1"](_session(), data_dir)
    got = df.collect()
    assert got == baselines["q1"]
    assert "Pipeline@query" not in df.metrics(), \
        "SRT_PIPELINE=0 must not open a pipeline"


# ---------------------------------------------------------------------------
# Counters
# ---------------------------------------------------------------------------

@requires_pipeline
def test_overlap_counters_flow(data_dir, baselines):
    before = PL.counters().get("prefetchedPartitions", 0)
    df = tpch.QUERIES["q1"](_session(), data_dir)
    assert df.collect() == baselines["q1"]
    m = df.metrics().get("Pipeline@query")
    assert m is not None, df.metrics().keys()
    assert m.get("hostPrefetchMs", 0) > 0, m
    assert m.get("prefetchedPartitions", 0) >= 1, m
    assert 0 <= m.get("overlapRatio", -1) <= 1, m
    g = PL.counters()
    assert g.get("prefetchedPartitions", 0) > before
    assert "overlapRatio" in g


@requires_pipeline
def test_concurrent_stage_materialization(data_dir):
    """Shuffled join (auto-broadcast off): the build- and probe-side
    exchanges are independent stages and materialize concurrently."""
    serial = tpch.QUERIES["q3"](_session(
        pipeline=False,
        **{"spark.rapids.sql.autoBroadcastJoinThreshold": -1}),
        data_dir).collect()
    df = tpch.QUERIES["q3"](_session(
        **{"spark.rapids.sql.autoBroadcastJoinThreshold": -1}), data_dir)
    got = df.collect()
    assert got == serial
    m = df.metrics().get("Pipeline@query")
    assert m is not None and m.get("concurrentStages", 0) >= 2, m


# ---------------------------------------------------------------------------
# Recovery parity: faults on prefetch threads re-raise at the ordered
# consumption point; the demotion ladder is unchanged
# ---------------------------------------------------------------------------

SCHEDULES = {
    "mixed": "transient@upload:1,oom@kernel:1,oom@upload:1",
    "scan-transient": "transient@scan:1,oom@concat:1",
}


@pytest.mark.parametrize("schedule", sorted(SCHEDULES))
@pytest.mark.parametrize("qname", QUERIES)
def test_bit_identical_under_faults(qname, schedule, baselines, data_dir):
    faults.reset_counters()
    df = tpch.QUERIES[qname](_session(SCHEDULES[schedule]), data_dir)
    got = df.collect()
    c = faults.counters()
    assert c.get("faultsInjected", 0) > 0, c
    assert got == baselines[qname], (
        f"{qname} under {schedule!r} diverged with the pipeline on")


def test_prefetch_fault_reraised_at_consumption(data_dir, baselines):
    """A transient raised on a PREFETCH thread surfaces at the ordered
    consumption point and recovers through the normal retry ladder."""
    faults.reset_counters()
    df = tpch.QUERIES["q1"](_session("transient@scan:1"), data_dir)
    got = df.collect()
    c = faults.counters()
    assert got == baselines["q1"]
    assert c.get("faultsInjected.transient@scan", 0) == 1, c
    assert c.get("retriesAttempted", 0) >= 1, c


def test_stall_on_prefetch_killed_by_watchdog(data_dir, baselines):
    """stall@scan hangs a prefetch thread; the watchdog kills the
    consuming attempt, the kill cancels the stalled prefetch, and the
    partition retry recomputes inline — bit-identical."""
    faults.reset_counters()
    s = _session("stall@scan:1")
    s.set("spark.rapids.sql.watchdog.enabled", True)
    s.set("spark.rapids.sql.watchdog.taskTimeoutMs", 1500)
    s.set("spark.rapids.sql.watchdog.maxAttempts", 3)
    got = tpch.QUERIES["q1"](s, data_dir).collect()
    c = faults.counters()
    assert got == baselines["q1"]
    assert c.get("watchdogKills", 0) >= 1, c
    assert c.get("partitionRetries", 0) >= 1, c


def test_stall_on_prefetch_without_watchdog_is_bounded(
        data_dir, baselines, monkeypatch):
    """Safety net: no watchdog armed, a stalled prefetch unwinds on its
    bounded timeout as DEADLINE_EXCEEDED -> transient retry."""
    monkeypatch.setattr(faults, "STALL_TIMEOUT_S", 0.2)
    faults.reset_counters()
    got = tpch.QUERIES["q1"](_session("stall@scan:1"), data_dir).collect()
    c = faults.counters()
    assert got == baselines["q1"]
    assert c.get("retriesAttempted", 0) >= 1, c


# ---------------------------------------------------------------------------
# No thread leaks
# ---------------------------------------------------------------------------

def test_no_lingering_prefetch_threads(data_dir):
    tpch.QUERIES["q1"](_session(), data_dir).collect()
    import time
    deadline = time.time() + 5
    while time.time() < deadline:
        alive = [t.name for t in threading.enumerate()
                 if t.name.startswith("srt-prefetch")
                 or t.name.startswith("srt-stage")]
        if not alive:
            return
        time.sleep(0.05)
    assert not alive, f"pipeline threads leaked: {alive}"
