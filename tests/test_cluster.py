"""Distributed worker runtime tests (ISSUE 13): stage-DAG partitioning,
pull-based locality scheduling, elastic membership, worker-death
recovery (SIGKILL chaos = exactly ONE stage recompute), exclusive-
manifest replacement semantics, and rendezvous client hardening.

Process-level tests launch real workers via
``python -m spark_rapids_tpu.parallel.cluster.worker`` and assert the
cluster result is BIT-IDENTICAL to the single-process run — the same
equality contract every other engine feature is held to.
"""

import base64
import json
import os
import socket
import subprocess
import sys
import threading
import time

import pytest

import spark_rapids_tpu
from spark_rapids_tpu import config as C
from spark_rapids_tpu import faults
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch
from spark_rapids_tpu.memory.oom import is_transient_error
from spark_rapids_tpu.parallel import cluster as CL
from spark_rapids_tpu.parallel import transport as T
from spark_rapids_tpu.parallel.cluster import coordinator as CO
from spark_rapids_tpu.parallel.transport import rendezvous as RV
from spark_rapids_tpu.parallel.transport.hostfile import HostFileTransport

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(spark_rapids_tpu.__file__)))


@pytest.fixture(autouse=True)
def clean_cluster_state():
    faults.configure("")
    faults.reset_counters()
    yield
    CL.shutdown_coordinator()
    faults.configure("")
    faults.reset_counters()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_cluster"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


def _session(**over) -> TpuSession:
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    for k, v in over.items():
        s.set(k, v)
    return s


def _cluster_session(**over) -> TpuSession:
    s = _session()
    s.set("spark.rapids.sql.cluster.enabled", True)
    for k, v in over.items():
        s.set(k, v)
    return s


def _spawn_worker(addr: str, wid: str, extra_env=None, heartbeat_ms=None):
    cmd = [sys.executable, "-m",
           "spark_rapids_tpu.parallel.cluster.worker",
           "--coordinator", addr, "--worker-id", wid]
    if heartbeat_ms is not None:
        cmd += ["--heartbeat-ms", str(heartbeat_ms)]
    env = dict(os.environ)
    env.pop("SRT_FAULTS", None)
    if extra_env:
        env.update(extra_env)
    return subprocess.Popen(cmd, env=env, cwd=REPO_ROOT)


def _stop(procs):
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=20)
        except Exception:
            p.kill()


def _dead_addr():
    """An address nothing listens on (bound once, then released)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return ("127.0.0.1", port)


# ---------------------------------------------------------------------------
# Stage-DAG partitioning
# ---------------------------------------------------------------------------

class TestStagePlan:
    def test_q3_dispatchable_stages_and_deps(self, data_dir):
        from spark_rapids_tpu.parallel.exchange import ShuffleExchangeExec
        s = _session()
        s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
        phys = tpch.QUERIES["q3"](s, data_dir)._physical()
        g, disp, deps = CO.stage_plan(phys.root)
        assert disp, "shuffle-forced q3 must have dispatchable stages"
        for sid in disp:
            assert isinstance(g.stages[sid].boundary, ShuffleExchangeExec)
        # The dep map only ever names dispatchable producers, and flows
        # transitively THROUGH non-dispatchable stages: q3's aggregate
        # exchange consumes join output, so at least one dispatchable
        # stage depends on another.
        for sid in disp:
            assert deps[sid] <= disp
        assert any(deps[sid] for sid in disp)

    def test_broadcast_stage_not_dispatchable_deps_flow_through(self):
        from spark_rapids_tpu.columnar import dtypes as dt
        from spark_rapids_tpu.parallel import SinglePartitioning
        from spark_rapids_tpu.parallel.exchange import (
            BroadcastExchangeExec, ShuffleExchangeExec)
        from test_ops import source
        src = source([("a", dt.INT64)], {"a": [1, 2, 3]})
        inner = ShuffleExchangeExec(src, SinglePartitioning())
        bx = BroadcastExchangeExec(inner)
        top = ShuffleExchangeExec(bx, SinglePartitioning())
        g, disp, deps = CO.stage_plan(top)
        bsid = g.by_exchange[id(bx)]
        isid = g.by_exchange[id(inner)]
        # Broadcast stages compute locally in every process (Spark
        # broadcast semantics) — never dispatched; their shuffle deps
        # flow THROUGH to whoever consumes the broadcast.
        assert bsid not in disp and isid in disp
        assert deps[bsid] == {isid}
        # the root exchange's own dispatchable stage sees the inner
        # shuffle THROUGH the broadcast stage between them
        tsid = g.by_exchange[id(top)]
        assert tsid in disp and deps[tsid] == {isid}


# ---------------------------------------------------------------------------
# Coordinator protocol: in-process verb-level tests (no worker processes)
# ---------------------------------------------------------------------------

def _submit_q3(data_dir, **over):
    s = _cluster_session(**over)
    s.set("spark.rapids.sql.autoBroadcastJoinThreshold", -1)
    phys = tpch.QUERIES["q3"](s, data_dir)._physical()
    co = CL.get_coordinator(s.conf)
    q = co.submit(phys, s.conf)
    assert q is not None
    return co, q


class TestCoordinatorProtocol:
    def test_register_poll_done_cycle(self, data_dir):
        co, q = _submit_q3(data_dir)
        assert co.dispatch(["CREG", "wA"]) == b"OK\n"
        seen = []
        while True:
            resp = co.dispatch(["CPOLL", "wA", "-"]).decode().split()
            if resp[0] == "CIDLE":
                break
            assert resp[0] == "CTASK"
            qid, sid, gen = int(resp[1]), int(resp[2]), int(resp[3])
            assert qid == q.qid and gen == 0
            # every dep of a dispatched task is already committed
            for d in q.tasks[sid].deps:
                assert q.tasks[d].status == "done"
            assert base64.b64decode(resp[5]).decode() == q.pkl_path
            assert co.dispatch(
                ["CDONE", "wA", str(qid), str(sid), str(gen),
                 "100"]) == b"OK\n"
            seen.append(sid)
        assert sorted(seen) == sorted(q.tasks)
        assert all(t.status == "done" and t.producer == "wA"
                   for t in q.tasks.values())

    def test_min_workers_gate_and_late_joiner_gets_work(self, data_dir):
        co, q = _submit_q3(
            data_dir, **{"spark.rapids.sql.cluster.minWorkers": 2})
        co.dispatch(["CREG", "wA"])
        resp = co.dispatch(["CPOLL", "wA", "-"]).decode()
        assert resp.startswith("CIDLE")       # gate closed at 1 worker
        co.dispatch(["CREG", "wB"])           # elastic late join
        # The joiner picks up queued work — possibly after waiting out
        # the steal-delay reservation on stages whose rendezvous-hash
        # owner is the (idle) incumbent.
        deadline = time.time() + 2.0
        while True:
            resp = co.dispatch(["CPOLL", "wB", "-"]).decode()
            if resp.startswith("CTASK") or time.time() > deadline:
                break
            time.sleep(0.02)
        assert resp.startswith("CTASK")
        assert q.tasks[int(resp.split()[2])].worker == "wB"

    def test_stale_generation_commit_ignored(self, data_dir):
        co, q = _submit_q3(data_dir)
        co.dispatch(["CREG", "wA"])
        resp = co.dispatch(["CPOLL", "wA", "-"]).decode().split()
        sid = int(resp[2])
        with co._lock:                        # worker declared dead
            q._requeue_locked(q.tasks[sid], "test-induced")
        # the zombie's late commit carries the old generation: ignored
        co.dispatch(["CDONE", "wA", str(q.qid), str(sid), "0", "77"])
        t = q.tasks[sid]
        assert t.status == "pending" and t.gen == 1 and t.retries == 1
        assert t.producer is None

    def test_locality_prefers_shard_holder(self):
        conf = _cluster_session().conf
        co = CL.get_coordinator(conf)
        tasks = {1: CO._StageTask(1, set()), 2: CO._StageTask(2, set()),
                 3: CO._StageTask(3, {1}), 4: CO._StageTask(4, {2})}
        q = CO.QueryRun(co, 99, conf, tasks, {})
        with co._lock:
            co.queries[99] = q
            co._touch_locked("wA")
            co._touch_locked("wB")
            for sid, wid in ((1, "wA"), (2, "wB")):
                t = tasks[sid]
                t.status, t.producer, t.bytes = "done", wid, 1000
            # each worker is offered the consumer of ITS OWN shards
            _, picked_a = q._pick_locked("wA")
            assert picked_a.sid == 3
            _, picked_b = q._pick_locked("wB")
            assert picked_b.sid == 4
            co.queries.pop(99)

    def test_score_ties_prefer_hrw_owner(self):
        # Leaf stages (no input shards yet) all score 0: the tie must
        # break to the stage's rendezvous-hash owner, not to whichever
        # worker polls first — repeat queries then land every stage on
        # the same process, keeping its kernel traces hot.
        conf = _cluster_session().conf
        co = CL.get_coordinator(conf)
        tasks = {s: CO._StageTask(s, set()) for s in range(1, 9)}
        q = CO.QueryRun(co, 98, conf, tasks, {})
        with co._lock:
            co.queries[98] = q
            co._touch_locked("wA")
            co._touch_locked("wB")
            owners = {s: CO._hrw_owner(["wA", "wB"], s) for s in tasks}
            by_owner = {w: sorted(s for s, o in owners.items() if o == w)
                        for w in ("wA", "wB")}
            assert by_owner["wA"] and by_owner["wB"]
            for wid in ("wA", "wB"):
                for expect in by_owner[wid]:
                    _, picked = q._pick_locked(wid)
                    assert picked.sid == expect, (wid, by_owner)
            # every stage went to its owner; nothing left to steal
            assert q._pick_locked("wA") is None
            co.queries.pop(98)

    def test_steal_delay_reserves_task_for_preferred_worker(self):
        # Delay scheduling: a ready task is reserved for its preferred
        # worker for stealDelayMs, so a momentarily busy worker keeps
        # its stages (and its kernel traces) instead of losing them to
        # whichever idle process polls first. After the reservation
        # expires any worker may take it (work conservation).
        conf = _cluster_session().conf
        co = CL.get_coordinator(conf)
        sid = next(s for s in range(1, 50)
                   if CO._hrw_owner(["wA", "wB"], s) == "wA")
        q = CO.QueryRun(co, 97, conf, {sid: CO._StageTask(sid, set())},
                        {})
        assert q.steal_delay_s > 0    # default reservation is on
        with co._lock:
            co.queries[97] = q
            co._touch_locked("wA")
            co._touch_locked("wB")
            assert q._pick_locked("wB") is None     # reserved for wA
            t = q.tasks[sid]
            assert t.status == "pending" and t.ready_ts is not None
            t.ready_ts -= q.steal_delay_s + 1.0     # reservation lapses
            _, picked = q._pick_locked("wB")        # now stealable
            assert picked.sid == sid and picked.worker == "wB"
            co.queries.pop(97)

    def test_retry_budget_exhaustion_fails_dispatch(self, data_dir):
        co, q = _submit_q3(
            data_dir, **{"spark.rapids.sql.cluster.maxTaskRetries": 1})
        t = next(iter(q.tasks.values()))
        with co._lock:
            q._requeue_locked(t, "first")
            assert q.error is None
            q._requeue_locked(t, "second")
            assert isinstance(q.error, CO.ClusterDispatchError)
        co.dispatch(["CREG", "wA"])
        resp = co.dispatch(["CPOLL", "wA", "-"]).decode()
        assert resp.startswith("CIDLE")       # failed query stops dispatch

    def test_poll_reports_stale_queries(self, data_dir):
        co, q = _submit_q3(data_dir)
        co.dispatch(["CREG", "wA"])
        q.finish()                            # query retired
        resp = co.dispatch(["CPOLL", "wA", str(q.qid)]).decode().split()
        assert resp[0] == "CIDLE" and str(q.qid) in resp[1].split(",")


# ---------------------------------------------------------------------------
# Exclusive-manifest replacement (the recompute-republication bugfix pin)
# ---------------------------------------------------------------------------

def _exclusive_conf(tmp_path, wid, **over):
    raw = {C.SHUFFLE_TRANSPORT_HOSTFILE_DIR.key: str(tmp_path),
           C.SHUFFLE_TRANSPORT_HOSTFILE_WORKER_ID.key: wid,
           C.SHUFFLE_TRANSPORT_HOSTFILE_EXCLUSIVE_MANIFEST.key: True}
    raw.update({getattr(C, k).key: v for k, v in over.items()})
    return C.TpuConf(raw)


def _kv_batch(keys, vals):
    import numpy as np
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.host import (HostBatch, HostColumn,
                                                host_to_device)
    return host_to_device(HostBatch(
        ("k", "v"),
        [HostColumn(dt.INT64, np.asarray(keys, np.int64),
                    np.ones(len(keys), bool)),
         HostColumn(dt.INT64, np.asarray(vals, np.int64),
                    np.ones(len(vals), bool))]))


def _rows(handles):
    from spark_rapids_tpu.columnar.host import device_to_host
    return [row for h in handles for row in device_to_host(h.get())
            .to_pylist()]


class TestExclusiveManifest:
    def test_recompute_commit_atomically_replaces_manifest(self, tmp_path):
        w1 = HostFileTransport().open(
            _exclusive_conf(tmp_path, "dead"), "s5", 2, owner=1)
        w1.write_shard(0, _kv_batch([1, 2], [10, 20]))
        w1.write_shard(1, _kv_batch([3], [30]))
        w1.commit()
        path = os.path.join(str(tmp_path), "s5", "exchange.manifest.json")
        with open(path, encoding="utf-8") as f:
            assert json.load(f)["worker"] == "dead"
        # The stage recomputes on a survivor: its commit must REPLACE the
        # dead worker's manifest wholesale — never merge with it, so no
        # fetcher can observe a mix of old and new shard sets.
        w2 = HostFileTransport().open(
            _exclusive_conf(tmp_path, "survivor"), "s5", 2, owner=1)
        w2.write_shard(0, _kv_batch([1, 2], [10, 20]))
        w2.write_shard(1, _kv_batch([3], [30]))
        w2.commit()
        with open(path, encoding="utf-8") as f:
            m = json.load(f)
        assert m["worker"] == "survivor"
        files = [e["file"] for es in m["shards"].values() for e in es]
        assert files and all(f.startswith("survivor/") for f in files)
        # exclusive mode: ONE tag-scoped manifest, not one per worker
        names = [n for n in os.listdir(os.path.join(str(tmp_path), "s5"))
                 if n.endswith(".manifest.json")]
        assert names == ["exchange.manifest.json"]
        r = HostFileTransport().open(
            _exclusive_conf(tmp_path, "reader"), "s5", 2, owner=1)
        assert len(r._load_manifests()) == 1
        assert _rows(r.fetch_shards(0)) == [(1, 10), (2, 20)]
        assert _rows(r.fetch_shards(1)) == [(3, 30)]

    def test_fetch_only_session_never_deletes_producer_spool(self,
                                                             tmp_path):
        w = HostFileTransport().open(
            _exclusive_conf(tmp_path, "prod"), "s1", 1, owner=1)
        w.write_shard(0, _kv_batch([7], [70]))
        w.commit()
        r = HostFileTransport().open(
            _exclusive_conf(tmp_path, "cons"), "s1", 1, owner=1)
        r.fetch_only = True
        assert _rows(r.fetch_shards(0)) == [(7, 70)]
        r.invalidate()
        r.close()
        # the producer's committed output must survive consumer teardown
        r2 = HostFileTransport().open(
            _exclusive_conf(tmp_path, "cons2"), "s1", 1, owner=1)
        assert _rows(r2.fetch_shards(0)) == [(7, 70)]


# ---------------------------------------------------------------------------
# Rendezvous client hardening (connect timeouts + bounded backoff)
# ---------------------------------------------------------------------------

class TestRendezvousHardening:
    def test_unreachable_addr_fails_fast_typed_and_transient(self):
        addr = _dead_addr()
        t0 = time.monotonic()
        with pytest.raises(RV.RendezvousUnavailableError) as ei:
            RV._roundtrip(addr, "PING x y\n", timeout_s=0.2, retries=2,
                          backoff_ms=10)
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0                  # bounded, not a 90s hang
        assert str(ei.value).startswith("UNAVAILABLE:")
        assert "3 attempt" in str(ei.value)
        # typed onto the recovery ladder: the planner's whole-query
        # retry rung treats it as transient
        assert is_transient_error(ei.value)

    def test_client_params_read_hardening_keys(self):
        conf = C.TpuConf({
            C.SHUFFLE_TRANSPORT_HOSTFILE_RV_CONNECT_TIMEOUT_MS.key: 250,
            C.SHUFFLE_TRANSPORT_HOSTFILE_RV_RETRIES.key: 5,
            C.SHUFFLE_TRANSPORT_HOSTFILE_RV_BACKOFF_MS.key: 20})
        assert RV.client_params(conf) == (0.25, 5, 20)

    def test_commit_degrades_to_polling_when_rendezvous_dead(self,
                                                             tmp_path):
        host, port = _dead_addr()
        conf = _exclusive_conf(
            tmp_path, "w",
            SHUFFLE_TRANSPORT_HOSTFILE_RV_CONNECT_TIMEOUT_MS=100,
            SHUFFLE_TRANSPORT_HOSTFILE_RV_RETRIES=0)
        raw = dict(conf.raw)
        raw[C.SHUFFLE_TRANSPORT_HOSTFILE_RENDEZVOUS.key] = \
            f"{host}:{port}"
        conf = C.TpuConf(raw)
        before = T.counters().get("rendezvousDegraded", 0)
        w = HostFileTransport().open(conf, "sx", 1, owner=1)
        w.write_shard(0, _kv_batch([1], [2]))
        w.commit()                 # must not raise: manifest is durable
        assert T.counters().get("rendezvousDegraded", 0) == before + 1
        r = HostFileTransport().open(conf, "sx", 1, owner=1)
        assert _rows(r.fetch_shards(0)) == [(1, 2)]


# ---------------------------------------------------------------------------
# Process-level: real workers, bit-identity, chaos, elasticity
# ---------------------------------------------------------------------------

FAST_QUERIES = ("q1", "q3")


class TestClusterProcess:
    def test_tpch_bit_identity_driver_plus_two_workers(self, data_dir):
        s = _session()
        want = {q: tpch.QUERIES[q](s, data_dir).collect()
                for q in FAST_QUERIES}
        sc = _cluster_session()
        co = CL.get_coordinator(sc.conf)
        addr = f"{co.addr[0]}:{co.addr[1]}"
        procs = [_spawn_worker(addr, f"w{i}") for i in range(2)]
        try:
            for q in FAST_QUERIES:
                assert tpch.QUERIES[q](sc, data_dir).collect() == want[q]
            st = co.stats()["workers"]
            assert {"w0", "w1"} <= set(st)
            # at least one stage actually ran remotely across the queries
            assert sum(w["completed"] for w in st.values()) >= 1
        finally:
            _stop(procs)

    @pytest.mark.slow
    def test_tpch_all_queries_bit_identical_two_workers(self, data_dir):
        s = _session()
        want = {q: tpch.QUERIES[q](s, data_dir).collect()
                for q in sorted(tpch.QUERIES)}
        sc = _cluster_session()
        co = CL.get_coordinator(sc.conf)
        addr = f"{co.addr[0]}:{co.addr[1]}"
        procs = [_spawn_worker(addr, f"w{i}") for i in range(2)]
        try:
            for q in sorted(tpch.QUERIES):
                assert tpch.QUERIES[q](sc, data_dir).collect() == \
                    want[q], q
        finally:
            _stop(procs)

    @pytest.mark.slow      # CI runs this via the worker-death entry
    def test_sigkill_worker_death_exactly_one_stage_recompute(
            self, data_dir):
        s = _session()
        want = tpch.QUERIES["q3"](s, data_dir).collect()
        sc = _cluster_session(
            **{"spark.rapids.sql.cluster.heartbeatTimeoutMs": 1500})
        co = CL.get_coordinator(sc.conf)
        addr = f"{co.addr[0]}:{co.addr[1]}"
        # The armed worker starts ALONE so it deterministically receives
        # the first stage task and SIGKILLs itself mid-stage; the
        # survivor spawns only after the coordinator declared the death,
        # so it can never steal the armed task first.
        procs = [_spawn_worker(
            addr, "w0", heartbeat_ms=500,
            extra_env={"SRT_FAULTS": "workerdeath@cluster.stage:1"})]

        def spawn_survivor():
            while True:
                st = co.stats()["workers"]
                if "w0" in st and not st["w0"]["alive"]:
                    break
                time.sleep(0.05)
            procs.append(_spawn_worker(addr, "w1", heartbeat_ms=500))

        threading.Thread(target=spawn_survivor, daemon=True).start()
        try:
            c0 = dict(faults.counters())
            got = tpch.QUERIES["q3"](sc, data_dir).collect()
            c1 = faults.counters()
            delta = lambda k: c1.get(k, 0) - c0.get(k, 0)
            assert got == want                       # bit-identical
            assert delta("clusterWorkerDeaths") == 1
            assert delta("stageRecomputes") == 1     # ONE stage, not more
            assert delta("retriesAttempted") == 0    # never a dead query
            assert procs[0].wait(timeout=10) == -9   # really SIGKILLed
        finally:
            _stop(procs)

    @pytest.mark.slow
    def test_elastic_worker_joins_mid_run_and_unblocks_query(
            self, data_dir):
        s = _session()
        want = tpch.QUERIES["q3"](s, data_dir).collect()
        # minWorkers=3 with only two workers up: the dispatch gate holds
        # every task, so the query can ONLY complete once the third
        # worker joins mid-run — deterministic proof of elasticity.
        sc = _cluster_session(
            **{"spark.rapids.sql.cluster.minWorkers": 3})
        co = CL.get_coordinator(sc.conf)
        addr = f"{co.addr[0]}:{co.addr[1]}"
        procs = [_spawn_worker(addr, f"w{i}") for i in range(2)]
        result = {}

        def run():
            result["got"] = tpch.QUERIES["q3"](sc, data_dir).collect()

        th = threading.Thread(target=run, daemon=True)
        th.start()
        try:
            while True:                 # both up, heartbeating, starved
                st = co.stats()
                if {"w0", "w1"} <= set(st["workers"]) and st["queries"]:
                    break
                time.sleep(0.05)
            time.sleep(0.5)
            assert th.is_alive()        # gate really held the dispatch
            assert all(t["status"] == "pending"
                       for q in co.stats()["queries"].values()
                       for t in q.values())
            procs.append(_spawn_worker(addr, "w2"))
            th.join(timeout=180)
            assert not th.is_alive() and result["got"] == want
        finally:
            _stop(procs)


# ---------------------------------------------------------------------------
# Stand-downs: cluster mode must be correct before it is clever
# ---------------------------------------------------------------------------

class TestStandDowns:
    def test_disabled_by_default_no_coordinator(self, data_dir):
        s = _session()
        assert not CO.cluster_enabled(s.conf)
        tpch.QUERIES["q1"](s, data_dir).collect()
        assert CO._CO is None           # nothing cluster-side was built

    def test_no_dispatchable_stage_stands_down(self):
        # An exchange-free plan (scan+filter+project) has no shuffle
        # stage to dispatch: the query must run locally — instantly,
        # with zero workers registered — instead of waiting on the gate.
        from spark_rapids_tpu.columnar import dtypes as dt
        from spark_rapids_tpu.plan.logical import col
        sc = _cluster_session(
            **{"spark.rapids.sql.cluster.dispatchTimeoutMs": 2000})
        df = sc.create_dataframe(
            {"k": ["a", "b", "c"], "v": [1, 2, 3]},
            [("k", dt.STRING), ("v", dt.INT32)])
        got = df.filter(col("v") > 1).select("k").collect()
        assert sorted(got) == [("b",), ("c",)]
