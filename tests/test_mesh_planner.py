"""Planner-lowered collective shuffle: full DataFrame queries execute over
the 8-virtual-CPU-device mesh (conftest) with mesh.enabled, and results
match the single-process exchange and the host oracle (VERDICT r1 item 4).
"""

import numpy as np
import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.plan.logical import agg_count, agg_sum, col


def _session(mesh: bool):
    s = TpuSession()
    s.set("spark.rapids.sql.mesh.enabled", mesh)
    return s


def _tables(s, n=800, parts=5):
    rng = np.random.default_rng(11)
    facts = s.create_dataframe(
        {"k": rng.integers(0, 37, n).tolist(),
         "v": rng.integers(-100, 100, n).tolist(),
         "tag": [f"t{i % 7}" for i in range(n)]},
        [("k", dt.INT64), ("v", dt.INT64), ("tag", dt.STRING)],
        num_partitions=parts)
    dims = s.create_dataframe(
        {"dk": list(range(37)), "w": [i * 10 for i in range(37)]},
        [("dk", dt.INT64), ("w", dt.INT64)], num_partitions=2)
    return facts, dims


def _q_groupby(s):
    facts, _ = _tables(s)
    return facts.group_by("k").agg(
        agg_sum(col("v")).alias("sv"), agg_count().alias("n")) \
        .order_by("k")


def _q_join_agg(s):
    facts, dims = _tables(s)
    j = facts.join_on(dims, ["k"], ["dk"], strategy="shuffle")
    return j.group_by("tag").agg(
        agg_sum(col("v") + col("w")).alias("s"),
        agg_count().alias("n")).order_by("tag")


@pytest.mark.parametrize("qf", [_q_groupby, _q_join_agg],
                         ids=["groupby", "join_agg"])
def test_mesh_matches_single_process(qf):
    mesh_rows = qf(_session(True)).collect()
    single_rows = qf(_session(False)).collect()
    host_rows = qf(_session(False)).collect_host()
    assert mesh_rows == single_rows
    assert mesh_rows == host_rows


def test_mesh_exchange_in_plan():
    from spark_rapids_tpu.parallel.mesh_exchange import MeshExchangeExec
    q = _q_groupby(_session(True))
    phys = q._physical()

    def find(e):
        if isinstance(e, MeshExchangeExec):
            return True
        return any(find(c) for c in e.children)
    assert find(phys.root), "mesh exchange not planned"


def test_mesh_repartition():
    s = _session(True)
    facts, _ = _tables(s)
    got = sorted(facts.repartition(8, "k").collect())
    want = sorted(facts.collect())
    assert got == want


def test_mesh_shape_mismatch_folds_onto_mesh():
    """A mesh exchange whose partition count != mesh size FOLDS the
    logical partitions onto the devices (ISSUE 6 satellite — counter
    meshPartitionFolds) instead of degrading to the single-process
    shuffle; results stay correct partition-for-partition."""
    from spark_rapids_tpu import faults
    from spark_rapids_tpu.parallel.mesh_exchange import MeshExchangeExec
    from spark_rapids_tpu.parallel.partitioning import HashPartitioning

    s = _session(True)
    q = _q_groupby(s)
    phys = q._physical()

    def rewrite(e):
        # Force the shape mismatch: re-point every planned mesh
        # exchange at a 3-way partitioning on the 8-device mesh.
        if isinstance(e, MeshExchangeExec):
            e.partitioning = HashPartitioning(
                e.partitioning.keys, 3)
        for c in e.children:
            rewrite(c)
    rewrite(phys.root)
    faults.reset_counters()
    got = phys.collect()
    want = _q_groupby(_session(False)).collect()
    assert got == want
    c = faults.counters()
    assert c.get("meshPartitionFolds", 0) >= 1
    assert not c.get("meshCollectiveSkipped")
    assert not c.get("meshDegrades")


def test_mesh_unsupported_partitioning_degrades_observably(caplog):
    """Shapes the collective genuinely cannot run (a non-jittable
    partitioning) still degrade OBSERVABLY — warning +
    meshCollectiveSkipped counter + single-process fallback — never a
    silent skip or an assert."""
    import logging

    from spark_rapids_tpu import faults
    from spark_rapids_tpu.parallel.mesh_exchange import MeshExchangeExec
    from spark_rapids_tpu.parallel.partitioning import HashPartitioning

    class HostBoundPartitioning(HashPartitioning):
        @property
        def jittable(self):
            return False

    s = _session(True)
    q = _q_groupby(s)
    phys = q._physical()

    def rewrite(e):
        if isinstance(e, MeshExchangeExec):
            e.partitioning = HostBoundPartitioning(
                e.partitioning.keys, e.partitioning.num_partitions)
        for c in e.children:
            rewrite(c)
    rewrite(phys.root)
    faults.reset_counters()
    with caplog.at_level(logging.WARNING, "spark_rapids_tpu"):
        got = phys.collect()
    want = _q_groupby(_session(False)).collect()
    assert got == want
    assert faults.counters().get("meshCollectiveSkipped", 0) >= 1
    assert any("mesh collective skipped" in r.message
               for r in caplog.records)


def test_two_phase_sized_exchange(monkeypatch):
    """The sizes-then-data mesh shuffle (SURVEY 7 hard part 6): with the
    threshold lowered, the counts collective sizes the data all_to_all's
    piece capacity below the worst case and results stay correct."""
    import spark_rapids_tpu.parallel.mesh_exchange as MX
    from spark_rapids_tpu import FLOAT64, INT64
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.plan.logical import agg_sum, col
    monkeypatch.setattr(MX, "TWO_PHASE_MIN_SHARD_ROWS", 8)
    import numpy as np
    rng = np.random.default_rng(5)
    n = 4096
    data = {"k": rng.integers(0, 97, n).tolist(),
            "v": rng.normal(size=n).tolist()}
    s = TpuSession()
    s.set("spark.rapids.sql.mesh.enabled", True)
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    df = s.create_dataframe(data, [("k", INT64), ("v", FLOAT64)],
                            num_partitions=8) \
        .group_by("k").agg(agg_sum(col("v")).alias("sv"))
    got = sorted(df.collect())
    want = sorted(df.collect_host())
    assert len(got) == 97
    for a, b in zip(got, want):
        assert a[0] == b[0] and abs(a[1] - b[1]) < 1e-9
