"""Task-context expression tests: spark_partition_id,
monotonically_increasing_id, rand, input_file_name.

Ref: GpuSparkPartitionID.scala, GpuMonotonicallyIncreasingID.scala,
GpuRandomExpressions.scala, GpuInputFileBlock.scala. Device and host
engines must agree exactly (the rand mixer is shared), so the standard
dual-engine harness applies even to the "nondeterministic" nodes.
"""

import os

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.api import (
    TpuSession, agg_count, col, input_file_name,
    monotonically_increasing_id, rand, spark_partition_id)

from harness import assert_rows_equal


@pytest.fixture
def session():
    return TpuSession()


DATA = {"v": list(range(20))}
SCHEMA = [("v", dt.INT32)]


def dual_collect(df, approx_float=False):
    dev, host = df.collect(), df.collect_host()
    keyf = lambda r: tuple((v is None, str(v)) for v in r)
    dev, host = sorted(dev, key=keyf), sorted(host, key=keyf)
    assert_rows_equal(dev, host, approx_float, "device vs host engine")
    return dev


class TestSparkPartitionID:
    def test_matches_partition(self, session):
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=3)
        rows = dual_collect(
            df.select("v", spark_partition_id().alias("pid")))
        pids = {p for _, p in rows}
        assert pids <= {0, 1, 2} and len(pids) > 1
        # Same v always lands in the same partition (stable assignment).
        assert len({(v, p) for v, p in rows}) == len(DATA["v"])


class TestMonotonicallyIncreasingID:
    def test_layout_and_uniqueness(self, session):
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=3)
        rows = dual_collect(
            df.select("v", monotonically_increasing_id().alias("mid")))
        mids = [m for _, m in rows]
        assert len(set(mids)) == len(mids)
        for _, m in rows:
            pid, ridx = m >> 33, m & ((1 << 33) - 1)
            assert 0 <= pid < 3
            assert 0 <= ridx < len(DATA["v"])

    def test_row_base_advances_across_batches(self, session):
        # Single partition, batch size forced tiny so multiple device
        # batches stream through one projection: ids must stay dense.
        s = TpuSession({"spark.rapids.sql.batchSizeRows": 4})
        df = s.range(20, num_partitions=1)
        rows = dual_collect(
            df.select("id", monotonically_increasing_id().alias("mid")))
        mids = sorted(m for _, m in rows)
        assert mids == list(range(20))


class TestRand:
    def test_range_and_determinism(self, session):
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        rows = dual_collect(df.select("v", rand(42).alias("r")))
        rs = [r for _, r in rows]
        assert all(0.0 <= r < 1.0 for r in rs)
        assert len(set(rs)) == len(rs)   # no repeats at this scale
        # Same seed → same values on a second run.
        rows2 = df.select("v", rand(42).alias("r")).collect()
        assert sorted(rows) == sorted(rows2)

    def test_adjacent_seeds_not_shifted_copies(self, session):
        # Regression: a raw linear counter made seed s+1's stream a one-row
        # shift of seed s's. The premixed seed must break that.
        from spark_rapids_tpu.exprs.nondeterministic import _uniform
        idx = np.arange(100, dtype=np.int64)
        pid = np.int64(0)
        u1 = _uniform(np, 1, pid, idx)
        u2 = _uniform(np, 2, pid, idx)
        assert not np.allclose(u1[1:], u2[:-1])
        assert not np.allclose(u2[1:], u1[:-1])
        assert abs(np.corrcoef(u1, u2)[0, 1]) < 0.3

    def test_seed_changes_stream(self, session):
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        r1 = {v: r for v, r in
              df.select("v", rand(1).alias("r")).collect()}
        r2 = {v: r for v, r in
              df.select("v", rand(2).alias("r")).collect()}
        assert any(r1[v] != r2[v] for v in r1)

    def test_filter_sampling(self, session):
        df = session.create_dataframe(
            {"v": list(range(2000))}, SCHEMA, num_partitions=2)
        out = dual_collect(df.filter(rand(7) < 0.5).select("v"))
        frac = len(out) / 2000
        assert 0.4 < frac < 0.6


class TestInputFileName:
    def test_reports_scanned_file(self, session, tmp_path):
        import pyarrow as pa
        import pyarrow.parquet as papq
        paths = []
        for i in range(3):
            p = str(tmp_path / f"part-{i}.parquet")
            papq.write_table(
                pa.table({"v": list(range(i * 10, i * 10 + 10))}), p)
            paths.append(p)
        df = session.read.parquet(*paths)
        rows = dual_collect(
            df.select("v", input_file_name().alias("f")))
        assert len(rows) == 30
        by_file = {}
        for v, f in rows:
            by_file.setdefault(f, []).append(v)
        assert set(by_file) == set(paths)
        for i, p in enumerate(paths):
            assert sorted(by_file[p]) == list(range(i * 10, i * 10 + 10))

    def test_empty_without_scan(self, session):
        df = session.create_dataframe(DATA, SCHEMA)
        rows = dual_collect(df.select(input_file_name().alias("f")))
        assert all(f == "" for (f,) in rows)

    def test_coalescing_reader_forced_perfile(self, tmp_path):
        # Regression: with the COALESCING reader, batches span files; the
        # planner must force PERFILE when input_file_name is present.
        import pyarrow as pa
        import pyarrow.parquet as papq
        s = TpuSession({
            "spark.rapids.sql.format.parquet.reader.type": "COALESCING"})
        paths = []
        for i in range(3):
            p = str(tmp_path / f"c-{i}.parquet")
            papq.write_table(pa.table({"v": [i * 2, i * 2 + 1]}), p)
            paths.append(p)
        df = s.read.parquet(*paths)
        rows = df.select("v", input_file_name().alias("f")).collect()
        assert {f for _, f in rows} == set(paths)


class TestAnalysisGuards:
    """Contextual expressions outside select/filter must fail loudly, not
    silently evaluate with a default task context."""

    def test_group_by_contextual_raises(self, session):
        from spark_rapids_tpu.plan.logical import ResolutionError
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        g = df.group_by(monotonically_increasing_id()).agg(n=agg_count())
        with pytest.raises(ResolutionError, match="task-context"):
            g.collect()

    def test_order_by_contextual_raises(self, session):
        from spark_rapids_tpu.plan.logical import ResolutionError
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        with pytest.raises(ResolutionError, match="task-context"):
            df.order_by(rand(42)).collect()
