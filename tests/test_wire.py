"""Wire codec (columnar/wire.py): lossless narrow-upload round trips.

The codec must be invisible: host_to_device(hb) -> device_to_host must
reproduce every value bit-exactly, for every dtype and every adversarial
float (NaN, inf, -0.0, denormals), with and without nulls.
"""

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar import wire
from spark_rapids_tpu.columnar.host import (HostBatch, HostColumn,
                                            device_to_host, host_to_device)


def roundtrip(dtype, values):
    hb = HostBatch.from_pydict([("x", dtype)], {"x": values})
    db = host_to_device(hb)
    back = device_to_host(db, ("x",))
    return back.columns[0].to_list(), db




def hi_card(base, dtype=None):
    """Append >1024 distinct filler values so the dictionary path declines
    and the typed-wire spec under test is the one chosen."""
    import numpy as np
    if dtype == "str":
        return list(base) + [f"filler-{i}" for i in range(1200)]
    return list(base) + [float(i) + 0.5 if dtype == "f" else (10 + i)
                         for i in range(1200)]

class TestWireRoundTrip:
    def test_int_narrowing_small(self):
        vals = [1, 2, None, 127, -128]
        out, db = roundtrip(dt.INT64, vals)
        assert out == vals
        # Wire dtype must actually be narrow on the encode side.
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.INT64, vals), "x", 5, 8, None)
        assert spec[2] == "int8"

    def test_int_no_narrowing_when_big(self):
        vals = [2 ** 40, -2 ** 40, None]
        out, _ = roundtrip(dt.INT64, vals)
        assert out == vals
        vals = hi_card([2 ** 40, -2 ** 40, None])
        vals += [v * 2 ** 30 for v in range(1300)]   # defeat int narrowing
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.INT64, vals), "x", len(vals), 4096,
            None)
        assert spec[2] == "int64"

    def test_float_2dp_ships_exact(self):
        # 2-decimal money values are NOT exactly a cast away from any
        # narrow type; the codec must NOT invent a scaled-int decode (the
        # device's emulated f64 divide is not correctly rounded), so these
        # ship as f64 (or f32 when exactly representable) and round-trip
        # bit-exactly.
        vals = [1234.56, 0.01, None, -99.99, 0.07]
        out, _ = roundtrip(dt.FLOAT64, vals)
        assert out == vals
        vals = hi_card(vals, "f")
        vals = [None if v is None else v + 0.003 for v in vals]
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", len(vals),
            4096, None)
        assert spec[2] == "float64"

    def test_float_whole_numbers(self):
        vals = [1.0, 50.0, None, -3.0]
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", 4, 8, None)
        assert spec[2] == "int8"
        out, _ = roundtrip(dt.FLOAT64, vals)
        assert out == vals

    def test_float_nan_inf_falls_back(self):
        vals = [1.5, float("nan"), float("inf"), None]
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", 4, 8, None)
        assert spec[2] == "float64"
        out, _ = roundtrip(dt.FLOAT64, vals)
        assert out[0] == 1.5 and np.isnan(out[1]) and out[2] == float("inf")

    def test_long_string_int32_lengths(self):
        # A >32767-byte string forces int32 wire lengths (int16 would wrap
        # and corrupt the data silently).
        big = "x" * 40000
        vals = [big, "short", None]
        out, _ = roundtrip(dt.STRING, vals)
        assert out == vals
        # Dictionary path: int32 lengths survive the dict len-table too.
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.STRING, vals), "x", 3, 8, None)
        assert spec[0] == "dstr" and spec[2] == "int8" and spec[1] > 32767
        # Typed path (high cardinality): int32 wire lengths.
        vals = hi_card([big, "short", None], "str")
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.STRING, vals), "x", len(vals), 4096,
            None)
        assert spec[0] == "str" and spec[2] == "int32"

    def test_negative_zero_preserved(self):
        vals = hi_card([-0.0, 1.0, 2.0], "f")
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", len(vals),
            4096, None)
        # -0.0 disqualifies the scaled-int path (it would become +0.0).
        assert spec[2] in ("float64", "float32")
        vals = [-0.0, 1.0, 2.0]
        out, _ = roundtrip(dt.FLOAT64, vals)
        assert np.signbit(np.float64(out[0]))

    def test_float_irrational_falls_back(self):
        vals = hi_card([np.pi, np.e, 1 / 3], "f")
        vals = [v + 1 / 3 for v in vals]
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", len(vals),
            4096, None)
        assert spec[2] == "float64"
        vals = [np.pi, np.e, 1 / 3]
        out, _ = roundtrip(dt.FLOAT64, vals)
        assert out == vals

    def test_f32_exact_representable(self):
        vals = hi_card([0.5, 0.25, 1.0 + 2 ** -20], "f")
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", len(vals),
            4096, None)
        assert spec[2] == "float32"
        vals = [0.5, 0.25, 1.0 + 2 ** -20]
        out, _ = roundtrip(dt.FLOAT64, vals)
        assert out == vals

    def test_strings_with_nulls(self):
        vals = ["hello", None, "", "wörld"]
        out, db = roundtrip(dt.STRING, vals)
        assert out == vals

    def test_bool(self):
        vals = [True, None, False, True]
        out, _ = roundtrip(dt.BOOL, vals)
        assert out == vals

    def test_all_valid_validity_elided(self):
        vals = [1, 2, 3]
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.INT32, vals), "x", 3, 8, None)
        assert spec[-1] == "all"
        assert len(arrs) == 1     # data only, no validity buffer

    def test_nulls_packed_validity(self):
        vals = [1, None, 3]
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.INT32, vals), "x", 3, 8, None)
        assert spec[-1] == "packed"
        assert arrs[-1].dtype == np.uint8 and arrs[-1].size == 1
        out, db = roundtrip(dt.INT32, vals)
        assert out == vals
        # Padding rows must read as invalid.
        validity = np.asarray(db.columns[0].validity)
        assert not validity[3:].any()

    def test_empty_batch(self):
        out, _ = roundtrip(dt.FLOAT64, [])
        assert out == []

    def test_date_narrows(self):
        vals = [8766, 9131, None, 10956]
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.DATE, vals), "x", 4, 8, None)
        assert spec[2] == "int16"
        out, _ = roundtrip(dt.DATE, vals)
        assert out == vals

    def test_rows_hint_set(self):
        hb = HostBatch.from_pydict([("x", dt.INT32)], {"x": [1, 2, 3]})
        db = host_to_device(hb)
        assert db.rows_hint == 3


class TestDictionaryWire:
    """Low-cardinality columns ship as codes + a value table (the wire's
    LZ4 stand-in: decode is ONE exact gather, no arithmetic)."""

    def test_string_dict(self):
        vals = (["MAIL", "SHIP", None, "AIR"] * 50)[:-1] + ["RAIL"]
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.STRING, vals), "x", len(vals), 256,
            None)
        assert spec[0] == "dstr"
        out, _ = roundtrip(dt.STRING, vals)
        assert out == vals

    def test_float_dict_bit_exact(self):
        base = [0.01 * i for i in range(11)] + [None]
        vals = base * 20
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", len(vals), 512,
            None)
        assert spec[0] == "dnum" and spec[2] == "int8"
        # -0.0 disqualifies the dict (factorize hashes it equal to +0.0).
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, [-0.0] + base[:-1] * 20),
            "x", 221, 256, None)
        assert spec[0] == "num"
        out, _ = roundtrip(dt.FLOAT64, vals)
        import numpy as np
        for got, want in zip(out, vals):
            if want is None:
                assert got is None
            else:
                assert np.float64(got).tobytes() == \
                    np.float64(want).tobytes()

    def test_int_dict(self):
        vals = ([2 ** 40, -2 ** 40, 7, None] * 40)
        out, _ = roundtrip(dt.INT64, vals)
        assert out == vals

    def test_padding_rows_decode_to_zero(self):
        vals = [5.5, 6.5]
        hb = HostBatch.from_pydict([("x", dt.FLOAT64)], {"x": vals * 80})
        db = host_to_device(hb)
        import numpy as np
        data = np.asarray(db.columns[0].data)
        assert (data[160:] == 0).all()


class TestCodecV2:
    """RLE / delta / frame-of-reference (codec v2): chosen by smallest
    wire size from host stats, decoded by gathers + exact integer
    arithmetic, bit-exact round trips per dtype."""

    def test_rle_sorted_floats(self):
        vals = [1.5] * 30 + [2.25] * 30 + [None] * 4 + [7.0] * 30
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", len(vals),
            128, None)
        assert spec[0] == "rle"
        out, _ = roundtrip(dt.FLOAT64, vals)
        assert out == vals

    def test_rle_bit_view_signed_zero_and_nan(self):
        # Run detection is on the BIT view: -0.0/0.0 and NaN runs must
        # not merge (a value-compare diff would fold them together and
        # gather the wrong bit pattern).
        vals = [-0.0] * 12 + [0.0] * 12 + [float("nan")] * 12 \
            + [1e300] * 12
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", len(vals),
            48, None)
        assert spec[0] == "rle"
        out, _ = roundtrip(dt.FLOAT64, vals)
        assert np.signbit(np.float64(out[0]))
        assert not np.signbit(np.float64(out[12]))
        assert np.isnan(out[24]) and out[36] == 1e300

    def test_delta_monotone_int64(self):
        # int8 deltas over a span past uint8 (so frame-of-reference
        # needs 2-byte offsets and delta's 1-byte diffs win): the codec
        # ships an int64 base + int8 deltas, decoded by exact cumsum.
        vals = [2 ** 40 + 7 * i for i in range(64)]
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.INT64, vals), "x", 64, 64, None)
        assert spec[0] == "delta" and spec[2] == "int8", spec
        out, _ = roundtrip(dt.INT64, vals)
        assert out == vals

    def test_delta_overflowing_diffs_decline(self):
        # Diffs that wrap int64 must either reconstruct exactly or
        # decline — never corrupt.
        vals = [-(2 ** 62), 2 ** 62, -(2 ** 62), 2 ** 62] * 16
        out, _ = roundtrip(dt.INT64, vals)
        assert out == vals

    def test_for_clustered_int64(self):
        rng = np.random.default_rng(0)
        vals = (10 ** 15 + rng.integers(0, 40_000, 64)).tolist()
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.INT64, vals), "x", 64, 64, None)
        assert spec[0] == "for" and spec[2] == "uint16"
        out, _ = roundtrip(dt.INT64, vals)
        assert out == vals

    def test_v2_padding_rows_decode_to_zero(self):
        for vals in ([3.5] * 40,                        # rle
                     [10 ** 15 + i * 7 for i in range(40)]):  # delta/for
            t = dt.FLOAT64 if isinstance(vals[0], float) else dt.INT64
            hb = HostBatch.from_pydict([("x", t)], {"x": vals})
            db = host_to_device(hb)
            data = np.asarray(db.columns[0].data)
            assert (data[len(vals):] == 0).all()
            assert not np.asarray(db.columns[0].validity)[len(vals):].any()

    def test_property_roundtrip_dtype_ladder(self):
        """Per-dtype property test: adversarial random data AND its
        sorted variant (the RLE/delta-friendly shape) round-trip
        bit-exactly through whatever codec wins."""
        import sys
        sys.path.insert(0, "tests")
        from data_gen import ALL_GENS, gen_batch
        import math
        for gen in ALL_GENS:
            for do_sort in (False, True):
                hb = gen_batch([("x", gen)], 96, seed=17)
                vals = hb.columns[0].to_list()
                if do_sort:
                    nn = [v for v in vals if v is not None]
                    nn.sort(key=lambda v: (
                        isinstance(v, float) and math.isnan(v), v))
                    vals = nn + [None] * 4
                out, _ = roundtrip(gen.dtype, vals)
                for got, want in zip(out, vals):
                    if want is None or got is None:
                        assert got is None and want is None, \
                            (gen.dtype.name, got, want)
                    elif isinstance(want, float):
                        assert np.float64(got).tobytes() == \
                            np.float64(want).tobytes() or (
                                np.isnan(got) and np.isnan(want)), \
                            (gen.dtype.name, got, want)
                    else:
                        assert got == want, (gen.dtype.name, got, want)

    def test_plain_and_v1_modes(self):
        from spark_rapids_tpu.config import TpuConf
        vals = [1.5] * 30 + [None] * 2 + [2.5] * 30
        try:
            wire.maybe_configure(TpuConf(
                {"spark.rapids.sql.wire.codec": "plain"}))
            arrs, spec = wire.encode_column(
                HostColumn.from_values(dt.FLOAT64, vals), "x",
                len(vals), 64, None)
            assert spec[0] == "num" and spec[2] == "float64"
            assert roundtrip(dt.FLOAT64, vals)[0] == vals
            wire.maybe_configure(TpuConf(
                {"spark.rapids.sql.wire.codec": "v1"}))
            arrs, spec = wire.encode_column(
                HostColumn.from_values(dt.FLOAT64, vals), "x",
                len(vals), 64, None)
            assert spec[0] in ("num", "dnum")       # never rle in v1
            assert roundtrip(dt.FLOAT64, vals)[0] == vals
        finally:
            wire.maybe_configure(TpuConf())
        arrs, spec = wire.encode_column(
            HostColumn.from_values(dt.FLOAT64, vals), "x", len(vals),
            64, None)
        assert spec[0] == "rle"                     # back to v2


class TestStagingBuffer:
    """Packed staging uploads: one aligned buffer, one transfer, and
    grouped tiny batches share a transfer bit-identically."""

    def test_offsets_aligned_and_layout_matches(self):
        hb = HostBatch.from_pydict(
            [("a", dt.INT64), ("b", dt.FLOAT64), ("s", dt.STRING)],
            {"a": [1, None, 3], "b": [1.5, 2.5, None],
             "s": ["xy", None, "zzz"]})
        enc = wire.pack_batch(hb)
        entries, total = wire._batch_layout(enc.cap, enc.specs)
        assert enc.staging.nbytes == total
        for off, _name, _shape, _nbytes in entries:
            assert off % 8 == 0

    def test_grouped_upload_bit_identical(self):
        hbs = [HostBatch.from_pydict(
            [("a", dt.INT64), ("b", dt.FLOAT64)],
            {"a": [i, None, i + 2], "b": [i + 0.5, 0.25 * i, None]})
            for i in range(6)]
        solo = [wire.upload_packed(wire.pack_batch(hb)) for hb in hbs]
        grouped = wire.upload_packed_group(
            [wire.pack_batch(hb) for hb in hbs])
        for a, b in zip(solo, grouped):
            from spark_rapids_tpu.columnar.host import device_to_host
            ra = device_to_host(a, ("a", "b")).to_pylist()
            rb = device_to_host(b, ("a", "b")).to_pylist()
            assert ra == rb

    def test_plan_upload_groups(self):
        # Tiny members accumulate to the threshold; big ones ship alone.
        assert wire.plan_upload_groups([10, 20, 2000, 5, 5, 5], 100) \
            == [[0, 1], [2], [3, 4, 5]]
        assert wire.plan_upload_groups([50, 60, 10], 100) \
            == [[0, 1], [2]]
        assert wire.plan_upload_groups([], 100) == []
        assert wire.plan_upload_groups([500], 100) == [[0]]
