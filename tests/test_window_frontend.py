"""Window functions through the public DataFrame API (ref:
GpuWindowExec.scala:92 planned via GpuOverrides.scala:1768 — here
LogicalWindow + planner exchange insertion + Column.over)."""

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.plan.logical import (
    Window, agg_avg, agg_count, agg_max, agg_sum, col, dense_rank, lag,
    lead, rank, row_number)

from harness import assert_rows_equal


@pytest.fixture
def session():
    return TpuSession()


@pytest.fixture
def df(session):
    return session.create_dataframe(
        {"g": ["a", "a", "b", "b", "b", None, "a"],
         "x": [3, 1, 5, 4, 2, 7, None],
         "y": [1.5, 2.5, 3.5, 4.5, 5.5, 6.5, 7.5]},
        [("g", srt.STRING), ("x", srt.INT64), ("y", srt.FLOAT64)],
        num_partitions=3)


def dual(frame):
    dev = sorted(frame.collect(), key=repr)
    host = sorted(frame.collect_host(), key=repr)
    assert_rows_equal(dev, host, approx_float=True,
                      msg="device vs host engine")
    return dev


class TestWindowFrontend:
    def test_row_number_rank(self, df):
        w = Window.partition_by("g").order_by(col("x").desc())
        out = dual(df.with_column("rn", row_number().over(w))
                     .with_column("rk", rank().over(w))
                     .with_column("dr", dense_rank().over(w)))
        by_g = {}
        for g, x, y, rn, rk, dr in out:
            by_g.setdefault(g, []).append((x, rn))
        # Nulls sort per spec; every partition numbers from 1.
        for g, rows in by_g.items():
            assert sorted(rn for _, rn in rows) == \
                list(range(1, len(rows) + 1))

    def test_running_and_whole_partition_aggs(self, df):
        w = Window.partition_by("g").order_by(col("x").asc())
        dual(df.with_column("rs", agg_sum(col("x")).over(w))
               .with_column("tot", agg_sum(col("x")).over(
                   Window.partition_by("g")))
               .with_column("cnt", agg_count(col("x")).over(
                   Window.partition_by("g")))
               .with_column("mx", agg_max(col("y")).over(
                   Window.partition_by("g"))))

    def test_rows_frame_and_lead_lag(self, df):
        w = Window.partition_by("g").order_by(col("x").asc())
        dual(df.with_column("ms", agg_avg(col("y")).over(
                 w.rows_between(-1, 1)))
               .with_column("nxt", lead(col("x")).over(w))
               .with_column("prv", lag(col("x")).over(w)))

    def test_unpartitioned_window(self, df):
        w = Window.order_by(col("x").asc())
        dual(df.with_column("rn", row_number().over(w)))

    def test_window_in_select(self, df):
        w = Window.partition_by("g").order_by(col("x").desc())
        out = dual(df.select("g", "x",
                             row_number().over(w).alias("rn")))
        assert all(len(r) == 3 for r in out)

    def test_window_then_filter_topk(self, df):
        """The TPC-DS q67 shape: rank within partition, keep rank <= k."""
        w = Window.partition_by("g").order_by(col("x").desc())
        out = dual(df.with_column("rk", rank().over(w))
                     .filter(col("rk") <= 2))
        for r in out:
            assert r[3] <= 2

    def test_rank_requires_order(self, df):
        from spark_rapids_tpu.plan.logical import ResolutionError
        bad = df.with_column("rk", rank().over(Window.partition_by("g")))
        with pytest.raises(ResolutionError):
            bad.collect()

    def test_explain_shows_window(self, df):
        w = Window.partition_by("g").order_by(col("x").asc())
        report = df.with_column("rn", row_number().over(w)).explain()
        assert "LogicalWindow" in report
