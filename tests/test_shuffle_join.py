"""Shuffle exchange, partitioning, and join tests (device vs host oracle).

Ref test models: GpuPartitioningSuite, HashAggregatesSuite join-side tests,
integration_tests join/repartition pytest files.
"""

import math

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch
from spark_rapids_tpu import exprs as E
from spark_rapids_tpu.exprs.base import BoundReference as Ref, lit
from spark_rapids_tpu.ops import (
    AggSpec, CountStar, HashAggregateExec, SortExec, SortOrder, Sum)
from spark_rapids_tpu.ops.join import (
    BroadcastHashJoinExec, BroadcastNestedLoopJoinExec, ShuffledHashJoinExec)
from spark_rapids_tpu.parallel import (
    BroadcastExchangeExec, HashPartitioning, RangePartitioning,
    RoundRobinPartitioning, ShuffleExchangeExec, SinglePartitioning)

from harness import assert_rows_equal
from test_ops import compare_engines, source


ORDERS_SCHEMA = [("o_key", dt.INT32), ("o_cust", dt.INT32),
                 ("o_total", dt.FLOAT64)]
ORDERS = {
    "o_key": [1, 2, 3, 4, 5, 6],
    "o_cust": [10, 20, 10, None, 30, 20],
    "o_total": [100.0, 200.0, 150.0, 50.0, 300.0, 250.0],
}
CUST_SCHEMA = [("c_key", dt.INT32), ("c_name", dt.STRING)]
CUST = {
    "c_key": [10, 20, 40, None],
    "c_name": ["alice", "bob", "dan", "ghost"],
}


class TestShuffleExchange:
    def test_hash_partition_preserves_rows(self):
        src = source(ORDERS_SCHEMA, ORDERS, num_partitions=2,
                     batches_per_partition=2)
        ex = ShuffleExchangeExec(src, HashPartitioning(
            [Ref(1, dt.INT32)], 4))
        dev = compare_engines(ex, sort_result=True)
        assert len(dev) == 6

    def test_hash_partition_device_host_same_buckets(self):
        # Same partition id per row on both engines (murmur3 parity).
        src = source(ORDERS_SCHEMA, ORDERS)
        ex = ShuffleExchangeExec(src, HashPartitioning(
            [Ref(1, dt.INT32)], 3))
        from spark_rapids_tpu.ops.base import ExecContext
        for p in range(3):
            ctx_d = ExecContext()
            ctx_h = ExecContext()
            dev_rows = []
            for b in ex.execute_device(ctx_d, p):
                from spark_rapids_tpu.columnar.host import device_to_host
                dev_rows.extend(device_to_host(b).to_pylist())
            host_rows = []
            for hb in ex.execute_host(ctx_h, p):
                host_rows.extend(hb.to_pylist())
            assert_rows_equal(dev_rows, host_rows, msg=f"partition {p}")

    def test_same_key_same_partition(self):
        src = source(ORDERS_SCHEMA, ORDERS)
        ex = ShuffleExchangeExec(src, HashPartitioning([Ref(1, dt.INT32)],
                                                       3))
        from spark_rapids_tpu.ops.base import ExecContext
        from spark_rapids_tpu.columnar.host import device_to_host
        ctx = ExecContext()
        seen = {}
        for p in range(3):
            for b in ex.execute_device(ctx, p):
                for row in device_to_host(b).to_pylist():
                    seen.setdefault(row[1], set()).add(p)
        for k, parts in seen.items():
            assert len(parts) == 1, f"key {k} split across {parts}"

    def test_round_robin(self):
        src = source(ORDERS_SCHEMA, ORDERS)
        ex = ShuffleExchangeExec(src, RoundRobinPartitioning(4))
        dev = compare_engines(ex, sort_result=True)
        assert len(dev) == 6

    def test_single(self):
        from spark_rapids_tpu.ops.base import ExecContext
        src = source(ORDERS_SCHEMA, ORDERS, num_partitions=3)
        ex = ShuffleExchangeExec(src, SinglePartitioning())
        assert ex.num_partitions(ExecContext()) == 1
        dev = compare_engines(ex, sort_result=True)
        assert len(dev) == 6

    def test_range_partition_orders_between_partitions(self):
        src = source(ORDERS_SCHEMA, ORDERS, num_partitions=2)
        ex = ShuffleExchangeExec(src, RangePartitioning(
            [SortOrder(Ref(0, dt.INT32))], 3))
        from spark_rapids_tpu.ops.base import ExecContext
        from spark_rapids_tpu.columnar.host import device_to_host
        ctx = ExecContext()
        maxes = []
        all_rows = []
        for p in range(3):
            vals = []
            for b in ex.execute_device(ctx, p):
                vals.extend(r[0] for r in device_to_host(b).to_pylist())
            all_rows.extend(vals)
            if vals:
                maxes.append((min(vals), max(vals)))
        assert sorted(all_rows) == [1, 2, 3, 4, 5, 6]
        for (lo1, hi1), (lo2, hi2) in zip(maxes, maxes[1:]):
            assert hi1 <= lo2

    def test_shuffle_then_two_stage_agg(self):
        # partial agg -> hash exchange on key -> final agg: the real
        # distributed aggregation plan shape.
        src = source(ORDERS_SCHEMA, ORDERS, num_partitions=2)
        partial = HashAggregateExec(
            src, [("cust", Ref(1, dt.INT32))],
            [AggSpec("total", Sum(Ref(2, dt.FLOAT64))),
             AggSpec("n", CountStar(None))], mode="partial")
        ex = ShuffleExchangeExec(partial,
                                 HashPartitioning([Ref(0, dt.INT32)], 3))
        final = HashAggregateExec(
            ex, [("cust", Ref(0, dt.INT32))],
            [AggSpec("total", Sum(Ref(2, dt.FLOAT64))),
             AggSpec("n", CountStar(None))], mode="final")
        compare_engines(final,
                        [(10, 250.0, 2), (20, 450.0, 2), (None, 50.0, 1),
                         (30, 300.0, 1)],
                        approx_float=True, sort_result=True)


def join_sources():
    left = source(ORDERS_SCHEMA, ORDERS, batches_per_partition=2)
    right = source(CUST_SCHEMA, CUST)
    return left, right


class TestJoins:
    def _expected_inner(self):
        out = []
        for ok, oc, ot in zip(ORDERS["o_key"], ORDERS["o_cust"],
                              ORDERS["o_total"]):
            for ck, cn in zip(CUST["c_key"], CUST["c_name"]):
                if oc is not None and ck is not None and oc == ck:
                    out.append((ok, oc, ot, ck, cn))
        return out

    def test_inner_broadcast(self):
        left, right = join_sources()
        plan = BroadcastHashJoinExec(
            left, right, [Ref(1, dt.INT32)], [Ref(0, dt.INT32)], "inner")
        compare_engines(plan, self._expected_inner(), sort_result=True)

    def test_inner_shuffled(self):
        # Co-partition both sides by key first.
        left, right = join_sources()
        lex = ShuffleExchangeExec(left,
                                  HashPartitioning([Ref(1, dt.INT32)], 3))
        rex = ShuffleExchangeExec(right,
                                  HashPartitioning([Ref(0, dt.INT32)], 3))
        plan = ShuffledHashJoinExec(
            lex, rex, [Ref(1, dt.INT32)], [Ref(0, dt.INT32)], "inner")
        compare_engines(plan, self._expected_inner(), sort_result=True)

    def test_left_outer(self):
        left, right = join_sources()
        plan = BroadcastHashJoinExec(
            left, right, [Ref(1, dt.INT32)], [Ref(0, dt.INT32)], "left")
        inner = self._expected_inner()
        matched = {r[0] for r in inner}
        expected = inner + [
            (ok, oc, ot, None, None)
            for ok, oc, ot in zip(ORDERS["o_key"], ORDERS["o_cust"],
                                  ORDERS["o_total"]) if ok not in matched]
        compare_engines(plan, expected, sort_result=True)

    def test_right_outer(self):
        left, right = join_sources()
        plan = BroadcastHashJoinExec(
            left, right, [Ref(1, dt.INT32)], [Ref(0, dt.INT32)], "right")
        inner = self._expected_inner()
        matched_c = {r[3] for r in inner}
        expected = inner + [
            (None, None, None, ck, cn)
            for ck, cn in zip(CUST["c_key"], CUST["c_name"])
            if ck not in matched_c]
        compare_engines(plan, expected, sort_result=True)

    def test_full_outer(self):
        left, right = join_sources()
        plan = BroadcastHashJoinExec(
            left, right, [Ref(1, dt.INT32)], [Ref(0, dt.INT32)], "full")
        inner = self._expected_inner()
        matched_o = {r[0] for r in inner}
        matched_c = {r[3] for r in inner}
        expected = inner + [
            (ok, oc, ot, None, None)
            for ok, oc, ot in zip(ORDERS["o_key"], ORDERS["o_cust"],
                                  ORDERS["o_total"])
            if ok not in matched_o] + [
            (None, None, None, ck, cn)
            for ck, cn in zip(CUST["c_key"], CUST["c_name"])
            if ck not in matched_c]
        compare_engines(plan, expected, sort_result=True)

    def test_semi_anti(self):
        left, right = join_sources()
        semi = BroadcastHashJoinExec(
            left, right, [Ref(1, dt.INT32)], [Ref(0, dt.INT32)], "semi")
        inner_keys = {r[0] for r in self._expected_inner()}
        expected = [(ok, oc, ot) for ok, oc, ot in
                    zip(ORDERS["o_key"], ORDERS["o_cust"],
                        ORDERS["o_total"]) if ok in inner_keys]
        compare_engines(semi, expected, sort_result=True)
        left2, right2 = join_sources()
        anti = BroadcastHashJoinExec(
            left2, right2, [Ref(1, dt.INT32)], [Ref(0, dt.INT32)], "anti")
        expected = [(ok, oc, ot) for ok, oc, ot in
                    zip(ORDERS["o_key"], ORDERS["o_cust"],
                        ORDERS["o_total"]) if ok not in inner_keys]
        compare_engines(anti, expected, sort_result=True)

    def test_inner_with_condition(self):
        left, right = join_sources()
        # join on key AND o_total > 150
        plan = BroadcastHashJoinExec(
            left, right, [Ref(1, dt.INT32)], [Ref(0, dt.INT32)], "inner",
            condition=E.GreaterThan(Ref(2, dt.FLOAT64), lit(150.0)))
        expected = [r for r in self._expected_inner() if r[2] > 150.0]
        compare_engines(plan, expected, sort_result=True)

    def test_left_with_condition(self):
        left, right = join_sources()
        plan = BroadcastHashJoinExec(
            left, right, [Ref(1, dt.INT32)], [Ref(0, dt.INT32)], "left",
            condition=E.GreaterThan(Ref(2, dt.FLOAT64), lit(150.0)))
        inner = [r for r in self._expected_inner() if r[2] > 150.0]
        matched = {r[0] for r in inner}
        expected = inner + [
            (ok, oc, ot, None, None)
            for ok, oc, ot in zip(ORDERS["o_key"], ORDERS["o_cust"],
                                  ORDERS["o_total"]) if ok not in matched]
        compare_engines(plan, expected, sort_result=True)

    def test_cross_join(self):
        left = source([("a", dt.INT32)], {"a": [1, 2, 3]})
        right = source([("b", dt.STRING)], {"b": ["x", "y"]})
        plan = BroadcastNestedLoopJoinExec(left, right, "cross")
        expected = [(a, b) for a in [1, 2, 3] for b in ["x", "y"]]
        compare_engines(plan, expected, sort_result=True)

    def test_string_join_keys(self):
        left = source([("k", dt.STRING), ("v", dt.INT32)],
                      {"k": ["a", "b", None, "c"], "v": [1, 2, 3, 4]})
        right = source([("k2", dt.STRING), ("w", dt.INT32)],
                       {"k2": ["a", "c", "d", None], "w": [10, 30, 40, 50]})
        plan = BroadcastHashJoinExec(
            left, right, [Ref(0, dt.STRING)], [Ref(0, dt.STRING)], "inner")
        compare_engines(plan, [("a", 1, "a", 10), ("c", 4, "c", 30)],
                        sort_result=True)

    def test_join_duplicate_build_keys(self):
        left = source([("k", dt.INT32)], {"k": [1, 1, 2]})
        right = source([("k2", dt.INT32), ("w", dt.STRING)],
                       {"k2": [1, 1, 1, 2], "w": ["a", "b", "c", "d"]})
        plan = BroadcastHashJoinExec(
            left, right, [Ref(0, dt.INT32)], [Ref(0, dt.INT32)], "inner")
        dev = compare_engines(plan, sort_result=True)
        assert len(dev) == 7  # 2 left rows x 3 matches + 1 x 1


class TestJoinReviewRegressions:
    def test_nested_loop_right_and_full(self):
        left = source([("a", dt.INT32)], {"a": [5]})
        right = source([("b", dt.INT32)], {"b": [1, 9]})
        # b > a condition: (5,9) matches; b=1 unmatched.
        plan = BroadcastNestedLoopJoinExec(
            left, right, "right",
            condition=E.GreaterThan(Ref(1, dt.INT32), Ref(0, dt.INT32)))
        compare_engines(plan, [(5, 9), (None, 1)], sort_result=True)
        plan = BroadcastNestedLoopJoinExec(
            source([("a", dt.INT32)], {"a": [5]}),
            source([("b", dt.INT32)], {"b": [1, 9]}), "full",
            condition=E.GreaterThan(Ref(1, dt.INT32), Ref(0, dt.INT32)))
        compare_engines(plan, [(5, 9), (None, 1)], sort_result=True)
        plan = BroadcastNestedLoopJoinExec(
            source([("a", dt.INT32)], {"a": [5, 99]}),
            source([("b", dt.INT32)], {"b": [1, 9]}), "left",
            condition=E.GreaterThan(Ref(1, dt.INT32), Ref(0, dt.INT32)))
        compare_engines(plan, [(5, 9), (99, None)], sort_result=True)

    def test_nested_loop_empty_build(self):
        left = source([("a", dt.INT32)], {"a": [1, 2]})
        right = source([("b", dt.INT32)], {"b": []})
        plan = BroadcastNestedLoopJoinExec(left, right, "left")
        compare_engines(plan, [(1, None), (2, None)], sort_result=True)
        plan = BroadcastNestedLoopJoinExec(
            source([("a", dt.INT32)], {"a": [1, 2]}),
            source([("b", dt.INT32)], {"b": []}), "cross")
        compare_engines(plan, [])

    def test_range_partition_host_engine(self):
        src = source(ORDERS_SCHEMA, ORDERS, num_partitions=2)
        ex = ShuffleExchangeExec(src, RangePartitioning(
            [SortOrder(Ref(0, dt.INT32))], 3))
        dev = compare_engines(ex, sort_result=True)
        assert len(dev) == 6


def test_nested_loop_with_filtered_small_build():
    """A small filtered build side keeps its selection vector past the
    broadcast (no shrink pull) — the NLJ must not pair probe rows with
    sel-deleted build rows."""
    from spark_rapids_tpu import FLOAT64, INT64
    from spark_rapids_tpu.api.dataframe import TpuSession
    from spark_rapids_tpu.plan.logical import col
    s = TpuSession()
    left = s.create_dataframe({"a": [1, 2, 3]}, [("a", INT64)])
    right = s.create_dataframe({"b": [10, 20, 30, 40]}, [("b", INT64)]) \
        .filter(col("b") >= 30)
    j = left.cross_join(right)
    got = sorted(j.collect())
    want = sorted(j.collect_host())
    assert got == want
    assert len(got) == 6        # 3 x 2, not 3 x 4
