"""Query flight recorder (ISSUE 9): span well-formedness, concurrent
attribution, chaos instants, trace-off bit-identity, Chrome export, and
explain_analyze.

Contract under test:
- every span begin has an end (open_span_count == 0 after a collect),
  durations are non-negative, and same-thread spans nest properly;
- a query's events land in ITS ring (the scheduler admission id), both
  serial and for two concurrent queries;
- injected oom/transient/lostshard schedules appear as ``fault-injected``
  / ``stage-recompute`` instants in the owning query's ring while the
  results stay bit-identical to the fault-free run;
- ``trace.enabled=false`` leaves results and metrics byte-identical and
  the recorder records nothing (the no-op path);
- ``trace_export`` emits Chrome trace-event JSON with the
  scheduler-queue / host-prefetch / device-compute / upload / shuffle
  categories on per-query, per-thread tracks;
- ``explain_analyze`` renders observed rows/bytes/wall next to the cost
  model's estimates with a per-node error.
"""

import json

import pytest

from spark_rapids_tpu import faults, monitoring
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import suites, tpch


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tpch_trace"))
    tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
    return d


@pytest.fixture(scope="module")
def suites_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("suites_trace"))
    suites.generate(d, scale=0.01, files_per_table=2)
    return d


@pytest.fixture(autouse=True)
def clean_state():
    faults.configure("")
    faults.reset_counters()
    monitoring.reset()
    yield
    monitoring.configure(False)
    monitoring.reset()


def _session(trace: bool = True, chaos: str = "", scan_cache: bool = True):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.trace.enabled", trace)
    s.set("spark.rapids.sql.test.faults", chaos)
    s.set("spark.rapids.sql.test.faults.seed", 7)
    s.set("spark.rapids.sql.retry.backoffMs", 1)
    if chaos or not scan_cache:
        s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    return s


def _query_events(df):
    """The traced query's own ring (attribution by admission id)."""
    ctx = df._physical().last_ctx
    qid = ctx.cache["trace_query"]
    return qid, monitoring.events(qid)


def _spans(evs):
    return [e for e in evs if e[0] == "X"]


def _instants(evs):
    return [e for e in evs if e[0] == "i"]


def _assert_well_formed(evs):
    assert monitoring.open_span_count() == 0, "unclosed span(s)"
    spans = _spans(evs)
    assert spans, "no spans recorded"
    for e in spans:
        assert e[3] >= 0 and e[4] >= 0, f"bad interval in {e!r}"
    # Same-thread spans must nest like a call stack: sort by (start,
    # -duration) and check each span closes within its enclosing one.
    by_tid = {}
    for e in spans:
        by_tid.setdefault(e[5], []).append(e)
    for tid, ss in by_tid.items():
        stack = []
        for e in sorted(ss, key=lambda e: (e[3], -e[4])):
            t0, t1 = e[3], e[3] + e[4]
            while stack and stack[-1] <= t0:
                stack.pop()
            if stack:
                assert t1 <= stack[-1], \
                    f"span {e[1]!r} partially overlaps its parent " \
                    f"(tid {tid})"
            stack.append(t1)


# ---------------------------------------------------------------------------
# Well-formedness: serial queries
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("qname", ["q1", "q6", "q3"])
def test_spans_well_formed_serial(qname, data_dir):
    df = tpch.QUERIES[qname](_session(), data_dir)
    df.collect()
    qid, evs = _query_events(df)
    assert qid > 0        # managed query: admission issued an id
    _assert_well_formed(evs)
    # Exactly one top-level collect span, and it brackets every
    # partition span of this query.
    collects = [e for e in _spans(evs)
                if e[1] == "collect" and e[2] == "query"]
    assert len(collects) == 1
    c0, c1 = collects[0][3], collects[0][3] + collects[0][4]
    parts = [e for e in _spans(evs) if e[1] == "partition"]
    assert parts
    for e in parts:
        assert c0 <= e[3] and e[3] + e[4] <= c1
    # Every event in the ring is attributed to this query.
    assert {e[6] for e in evs} == {qid}


def test_disabled_recorder_records_nothing(data_dir):
    df = tpch.QUERIES["q1"](_session(trace=False), data_dir)
    df.collect()
    assert monitoring.events() == []
    assert not monitoring.enabled()
    # The disabled span path returns the shared no-op (no allocation).
    s1 = monitoring.span("a", "b")
    s2 = monitoring.span("c", "d")
    assert s1 is s2


# ---------------------------------------------------------------------------
# Concurrent queries: per-query attribution
# ---------------------------------------------------------------------------

def test_two_concurrent_queries_attributed(data_dir):
    df_a = tpch.QUERIES["q6"](_session(), data_dir)
    df_b = tpch.QUERIES["q1"](_session(), data_dir)
    want_a = df_a.collect()
    want_b = df_b.collect()
    monitoring.reset()
    ha, hb = df_a.submit(), df_b.submit()
    assert ha.result(120) == want_a
    assert hb.result(120) == want_b
    qa, evs_a = _query_events(df_a)
    qb, evs_b = _query_events(df_b)
    assert qa != qb
    _assert_well_formed(evs_a)
    _assert_well_formed(evs_b)
    for qid, evs in ((qa, evs_a), (qb, evs_b)):
        assert {e[6] for e in evs} == {qid}
        assert sum(1 for e in _spans(evs)
                   if e[1] == "collect" and e[2] == "query") == 1


# ---------------------------------------------------------------------------
# Chaos: injected faults appear as instants, results bit-identical
# ---------------------------------------------------------------------------

def test_chaos_instants_oom_transient(data_dir):
    want = tpch.QUERIES["q3"](_session(chaos=""), data_dir).collect()
    monitoring.reset()
    df = tpch.QUERIES["q3"](
        _session(chaos="oom@upload:1,transient@download:1"), data_dir)
    got = df.collect()
    assert got == want       # bit-identical under the schedule
    qid, evs = _query_events(df)
    _assert_well_formed(monitoring.events())
    kinds = {(e[7] or {}).get("kind") for e in _instants(evs)
             if e[1] == "fault-injected"}
    assert {"oom", "transient"} <= kinds
    # OOM ladder rungs are instants too, attributed to the same query.
    assert any(e[1] == "oom-rung" for e in _instants(evs))


def test_chaos_instants_lostshard(data_dir, tmp_path):
    want = tpch.QUERIES["q3"](_session(chaos=""), data_dir).collect()
    monitoring.reset()
    s = _session(chaos="lostshard@transport:1")
    s.set("spark.rapids.sql.shuffle.transport", "hostfile")
    s.set("spark.rapids.sql.shuffle.transport.hostfile.dir",
          str(tmp_path))
    df = tpch.QUERIES["q3"](s, data_dir)
    got = df.collect()
    assert got == want
    qid, evs = _query_events(df)
    inst = _instants(evs)
    assert any(e[1] == "fault-injected"
               and (e[7] or {}).get("kind") == "lostshard" for e in inst)
    # The lineage-scoped recompute shows on the same timeline.
    assert any(e[1] == "stage-recompute" for e in inst)


def test_chaos_scoped_to_one_of_two_queries(data_dir):
    """Cross-query attribution: chaos scoped to query A must not leave
    instants in concurrent query B's ring."""
    df_a = tpch.QUERIES["q6"](_session(), data_dir)
    df_b = tpch.QUERIES["q1"](_session(), data_dir)
    want_a, want_b = df_a.collect(), df_b.collect()
    monitoring.reset()
    faults.configure("oom@upload/query=1:1", seed=7)
    ha, hb = df_a.submit(), df_b.submit()
    ra, rb = ha.result(120), hb.result(120)
    assert ra == want_a and rb == want_b
    qa, evs_a = _query_events(df_a)
    qb, evs_b = _query_events(df_b)
    tagged = {qid for qid in (qa, qb)
              if any(e[1] == "fault-injected"
                     for e in _instants(monitoring.events(qid)))}
    # The schedule names fault tag 1: at most that one query's ring
    # carries injection instants; the other stays clean.
    other = {qa, qb} - tagged
    for qid in other:
        assert not any(e[1] == "fault-injected"
                       for e in _instants(monitoring.events(qid)))


# ---------------------------------------------------------------------------
# trace.enabled=false: byte-identical results/metrics, no-op recorder
# ---------------------------------------------------------------------------

_TPCH_FAST = ["q1", "q6"]
_TPCH_SLOW = ["q3", "q5", "q12", "q14"]
_SUITES_FAST = ["repart"]
_SUITES_SLOW = ["q67", "xbb_q5", "ds_q3", "xbb_q12"]


# Counters keyed to PROCESS-GLOBAL cache state (kernel/scan caches warm
# monotonically across collects) — legitimately run-order-dependent,
# excluded from the trace-on/off shape comparison.
_CACHE_COUNTERS = {"kernelCacheHits", "kernelCacheMisses", "compileTime",
                   "scanCacheHits", "persistentCacheHits",
                   "planCacheMiss", "planCacheBindOnly"}


def _metric_shape(metrics: dict):
    """Instance-address-free metric shape: a sorted multiset of
    (operator name, counter names) — comparable across separately
    planned DataFrames."""
    return sorted((k.split("@")[0],
                   tuple(sorted(n for n in v
                                if n not in _CACHE_COUNTERS)))
                  for k, v in metrics.items())


def _identity_check(qname, mod, ddir):
    off = mod.QUERIES[qname](_session(trace=False, scan_cache=False),
                             ddir)
    rows_off = off.collect()
    metrics_off = off.metrics()
    assert monitoring.events() == []
    on = mod.QUERIES[qname](_session(trace=True, scan_cache=False), ddir)
    rows_on = on.collect()
    assert rows_on == rows_off
    assert monitoring.events() != []
    off2 = mod.QUERIES[qname](_session(trace=False, scan_cache=False),
                              ddir)
    assert off2.collect() == rows_off
    # Metric SHAPE is unchanged by a traced run in between (values are
    # timings): same operator entries, same counter names.
    assert _metric_shape(metrics_off) == _metric_shape(off2.metrics())
    assert _metric_shape(metrics_off) == _metric_shape(on.metrics())


@pytest.mark.parametrize("qname", _TPCH_FAST + [
    pytest.param(q, marks=pytest.mark.slow) for q in _TPCH_SLOW])
def test_trace_off_identity_tpch(qname, data_dir):
    _identity_check(qname, tpch, data_dir)


@pytest.mark.parametrize("qname", _SUITES_FAST + [
    pytest.param(q, marks=pytest.mark.slow) for q in _SUITES_SLOW])
def test_trace_off_identity_suites(qname, suites_dir):
    _identity_check(qname, suites, suites_dir)


# ---------------------------------------------------------------------------
# Chrome export (the Perfetto acceptance artifact)
# ---------------------------------------------------------------------------

def test_trace_export_chrome_q3(data_dir, tmp_path):
    # Scan cache off so the upload funnel actually runs (a cache hit
    # would serve device batches without crossing the wire).
    df = tpch.QUERIES["q3"](_session(scan_cache=False), data_dir)
    df.collect()
    path = str(tmp_path / "q3_trace.json")
    doc = df.trace_export(path)
    on_disk = json.load(open(path))
    assert on_disk == doc
    evs = doc["traceEvents"]
    assert evs
    # The acceptance categories, each on a real track.
    cats = {e.get("cat") for e in evs if e.get("ph") == "X"}
    assert {"queued", "host-prefetch", "device-compute", "upload",
            "shuffle"} <= cats, cats
    # One process track per query with a name; thread tracks named.
    pnames = [e for e in evs
              if e.get("ph") == "M" and e["name"] == "process_name"]
    assert pnames and all(
        a["args"]["name"].startswith("query ") for a in pnames)
    tnames = [e for e in evs
              if e.get("ph") == "M" and e["name"] == "thread_name"]
    assert tnames
    # Worker threads (prefetch pool) appear as their own tracks.
    tids = {e["tid"] for e in evs if e.get("ph") == "X"}
    assert len(tids) >= 2
    # Complete events carry microsecond ts/dur as the format requires.
    for e in evs:
        if e.get("ph") == "X":
            assert e["dur"] >= 0 and e["ts"] >= 0


def test_process_tag_prefixes_exported_tracks():
    # Cluster worker processes tag themselves (worker.py run()) so their
    # per-process trace exports render "worker <wid> query N" tracks;
    # the untagged driver keeps the plain "query N" names.
    from spark_rapids_tpu.monitoring.chrome import to_chrome
    evs = [("X", "stage", "cluster", 1_000, 2_000, 1, 3, None)]
    try:
        monitoring.set_process_tag("worker w7")
        doc = to_chrome(evs, {1: "t"}, monitoring.process_tag())
        names = [e["args"]["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert names == ["worker w7 query 3"]
    finally:
        monitoring.set_process_tag("")
    doc = to_chrome(evs, {1: "t"}, monitoring.process_tag())
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"]
    assert names == ["query 3"]


def test_snapshot_category_breakdown(data_dir):
    tpch.QUERIES["q6"](_session(), data_dir).collect()
    snap = monitoring.snapshot()
    assert snap["enabled"] and snap["openSpans"] == 0
    cats = snap["categories"]
    assert "device-compute" in cats and cats["device-compute"]["ms"] > 0
    assert "queued" in cats
    bd = monitoring.category_breakdown()
    assert bd.keys() == cats.keys()


# ---------------------------------------------------------------------------
# explain_analyze
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("qname", ["q1", "q6", "q3"])
def test_explain_analyze_tpch(qname, data_dir, capsys):
    s = _session()
    # Keep placement off (explicitly — estimates in explain_analyze come
    # from estimate_plan directly, independent of placement) so the
    # device engine runs and leaf operators record observed rows.
    s.set("spark.rapids.sql.cost.enabled", False)
    df = tpch.QUERIES[qname](s, data_dir)
    df.collect()
    out = df.explain_analyze()
    assert "rows=" in out and "wall=" in out and "bytes=" in out
    assert "est " in out and "err=" in out and "syncs" in out
    # Observed leaf rows are real numbers, not all '?'.
    assert any("rows=" in ln and "rows=?" not in ln
               for ln in out.splitlines())
    # The audit entries + the per-query category breakdown land in the
    # footer.
    assert "Scheduler@query" in out
    assert "Trace@query" in out and "device-compute=" in out


@pytest.mark.slow
@pytest.mark.parametrize("qname,pack", [(q, "tpch") for q in
                                        ["q1", "q6", "q3", "q5", "q12",
                                         "q14"]] +
                         [(q, "suites") for q in
                          ["repart", "q67", "xbb_q5", "ds_q3",
                           "xbb_q12"]])
def test_explain_analyze_full_suite(qname, pack, data_dir, suites_dir):
    """The 11-query acceptance sweep: explain_analyze renders observed
    numbers and estimate errors for every bench query."""
    mod, ddir = (tpch, data_dir) if pack == "tpch" else \
        (suites, suites_dir)
    df = mod.QUERIES[qname](_session(), ddir)
    df.collect()
    out = df.explain_analyze()
    assert "wall=" in out and "rows=" in out
    assert "est " in out and "err=" in out
