"""Out-of-core sort (VERDICT r4 item 7): sorting a partition LARGER than
the device budget completes via the sample-sort spill path and matches
the host oracle — beyond the reference's v0.3 RequireSingleBatch
(GpuSortExec.scala:50)."""

import numpy as np
import pytest

from spark_rapids_tpu import FLOAT64, INT64
from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.plan.logical import col


def _session(budget_bytes):
    s = TpuSession()
    s.set("spark.rapids.memory.tpu.budgetBytes", budget_bytes)
    return s


def _data(n, seed=7):
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(0, 1_000_000, n).tolist(),
            "v": rng.normal(size=n).tolist()}


def test_sort_larger_than_device_budget():
    n = 40_000
    data = _data(n)
    # ~40k rows x 2 f64 columns ~= 640KB of data; 256KB budget forces the
    # sample-sort split (plus spilling of the staged input).
    s = _session(256 * 1024)
    df = s.create_dataframe(data, [("k", INT64), ("v", FLOAT64)],
                            num_partitions=8) \
        .order_by(col("k").asc(), col("v").asc())
    got = df.collect()
    want = df.collect_host()
    assert got == want
    # The out-of-core path actually engaged (bucketed sort + spills).
    phys = df._physical()
    metrics = phys.last_ctx.metrics
    sort_m = [m for k, m in metrics.items() if "SortExec" in k]
    assert any(m.values.get("outOfCoreBuckets", 0) >= 2 for m in sort_m)


def test_sort_in_core_path_unchanged():
    data = _data(5_000)
    s = TpuSession()
    df = s.create_dataframe(data, [("k", INT64), ("v", FLOAT64)],
                            num_partitions=3) \
        .order_by(col("k").desc(), col("v").asc())
    assert df.collect() == df.collect_host()


def test_window_larger_than_device_budget():
    """Partition-chunked windows (the other half of VERDICT item 7):
    a partitioned window over data beyond the device budget range-splits
    by partition key and matches the host oracle."""
    from spark_rapids_tpu.plan.logical import agg_sum, col
    n = 40_000
    rng = np.random.default_rng(11)
    data = {"g": rng.integers(0, 500, n).tolist(),
            "v": rng.normal(size=n).tolist()}
    s = _session(96 * 1024)
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    df = s.create_dataframe(data, [("g", INT64), ("v", FLOAT64)],
                            num_partitions=8)
    from spark_rapids_tpu.plan.logical import Window
    w = Window.partition_by(col("g"))
    out = df.with_column("s", agg_sum(col("v")).over(w))
    got = sorted(out.collect())
    want = sorted(out.collect_host())
    assert len(got) == n
    for a, b in zip(got, want):
        assert a[:2] == b[:2] and abs(a[2] - b[2]) < 1e-9
    phys = out._physical()
    wms = [m.values for k, m in phys.last_ctx.metrics.items()
           if "WindowExec" in k]
    assert any(v.get("outOfCoreBuckets", 0) >= 2 for v in wms)
