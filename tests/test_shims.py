"""JAX version shim SPI (SparkShims.scala:61 analog)."""

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu import shims


def test_provider_names_resolved_shims():
    p = shims.provider()
    assert jax.__version__ in p and "shard-map" in p


def test_tree_roundtrip():
    tree = {"a": jnp.arange(3), "b": (jnp.ones(2), jnp.zeros(1))}
    leaves, treedef = shims.tree_flatten(tree)
    back = shims.tree_unflatten(treedef, leaves)
    assert set(back) == {"a", "b"}
    doubled = shims.tree_map(lambda x: x * 2, tree)
    assert np.array_equal(np.asarray(doubled["a"]), [0, 2, 4])


def test_shard_map_runs_on_mesh():
    from jax.sharding import Mesh, PartitionSpec as P
    devs = np.array(jax.devices())
    mesh = Mesh(devs, ("d",))
    n = len(devs)

    def local(x):
        return x * 2

    fn = jax.jit(shims.shard_map(local, mesh, in_specs=(P("d"),),
                                 out_specs=P("d")))
    x = jnp.arange(n * 4, dtype=jnp.float32).reshape(n, 4)
    out = fn(x)
    assert np.array_equal(np.asarray(out), np.asarray(x) * 2)
