"""Typed fuzzer generators (ref: integration_tests/src/main/python/
data_gen.py:26-491).

Seeded per-type generators with adversarial special-value injection —
NaN/±0.0/±inf for floats, min/max for integrals, empty/long/multibyte for
strings, nulls everywhere — plus frame builders (`gen_df`, `unary_op_df`,
`binary_op_df`) feeding the dual-engine compare harness. The point
(mirrors the reference): the CPU-vs-device equality harness only finds
corner-case bugs if the data contains the corners.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.host import HostBatch, HostColumn


class DataGen:
    """One column's generator. ``special`` values are injected with
    probability ``special_prob`` each row; ``nullable`` injects None."""

    dtype: dt.DataType
    special: Sequence = ()

    def __init__(self, nullable: bool = True, special_prob: float = 0.15,
                 null_prob: float = 0.12):
        self.nullable = nullable
        self.special_prob = special_prob
        self.null_prob = null_prob

    def _base(self, rng: np.random.Generator):
        raise NotImplementedError

    def gen(self, rng: np.random.Generator, n: int) -> list:
        out = []
        for _ in range(n):
            r = rng.random()
            if self.nullable and r < self.null_prob:
                out.append(None)
            elif self.special and r < self.null_prob + self.special_prob:
                out.append(self.special[int(rng.integers(
                    len(self.special)))])
            else:
                out.append(self._base(rng))
        return out


class BooleanGen(DataGen):
    dtype = dt.BOOL

    def _base(self, rng):
        return bool(rng.integers(2))


class _IntGen(DataGen):
    lo: int
    hi: int

    @property
    def special(self):
        return (self.lo, self.hi, 0, -1, 1)

    def _base(self, rng):
        return int(rng.integers(self.lo, self.hi, endpoint=True))


class ByteGen(_IntGen):
    dtype = dt.INT8
    lo, hi = -128, 127


class ShortGen(_IntGen):
    dtype = dt.INT16
    lo, hi = -(2 ** 15), 2 ** 15 - 1


class IntegerGen(_IntGen):
    dtype = dt.INT32
    lo, hi = -(2 ** 31), 2 ** 31 - 1


class LongGen(_IntGen):
    dtype = dt.INT64
    lo, hi = -(2 ** 63), 2 ** 63 - 1


class FloatGen(DataGen):
    dtype = dt.FLOAT32
    special = (float("nan"), float("inf"), float("-inf"), 0.0, -0.0,
               1.0, -1.0, 3.4028235e38, -3.4028235e38, 1.17549435e-38)

    def _base(self, rng):
        return float(np.float32(rng.normal(0, 1e6)))


class DoubleGen(DataGen):
    dtype = dt.FLOAT64
    # No subnormals (5e-324): XLA flushes them to zero (FTZ) while numpy
    # keeps them — a known accelerator divergence, same class of corner
    # the reference gates rather than fixes.
    special = (float("nan"), float("inf"), float("-inf"), 0.0, -0.0,
               1.0, -1.0, 1.7976931348623157e308)

    def _base(self, rng):
        return float(rng.normal(0, 1e12))


class StringGen(DataGen):
    dtype = dt.STRING
    special = ("", " ", "  leading", "trailing  ", "héllo wörld",
               "\t\n", "a" * 60, "%percent%", "_under_")

    _ALPHA = "abcdefghijklmnopqrstuvwxyzABCXYZ0123456789 ,.;-"

    def _base(self, rng):
        n = int(rng.integers(0, 12))
        return "".join(self._ALPHA[int(rng.integers(len(self._ALPHA)))]
                       for _ in range(n))


class DateGen(DataGen):
    dtype = dt.DATE
    # Days since epoch: cover pre-epoch, leap years, far future.
    special = (0, -1, -719162, 2932896, 18321, 10957)

    def _base(self, rng):
        return int(rng.integers(-30000, 30000))


class TimestampGen(DataGen):
    dtype = dt.TIMESTAMP
    special = (0, -1, 1, 86399999999, -62135596800000000)

    def _base(self, rng):
        return int(rng.integers(-2 ** 44, 2 ** 44))


class RepeatSeqGen(DataGen):
    """Cycles a small pool of values — makes join/groupby keys collide
    (data_gen.py RepeatSeqGen)."""

    def __init__(self, inner: DataGen, length: int = 8, seed: int = 7,
                 **kw):
        super().__init__(nullable=inner.nullable, **kw)
        self.dtype = inner.dtype
        rng = np.random.default_rng(seed)
        self.pool = [inner._base(rng) for _ in range(length)]
        if inner.nullable:
            self.pool[0] = None
        self._i = 0

    def gen(self, rng, n):
        out = []
        for _ in range(n):
            out.append(self.pool[self._i % len(self.pool)])
            self._i += 1
        return out


ALL_GENS: List[DataGen] = [
    BooleanGen(), ByteGen(), ShortGen(), IntegerGen(), LongGen(),
    FloatGen(), DoubleGen(), StringGen(), DateGen(), TimestampGen(),
]

NUMERIC_GENS = [ByteGen(), ShortGen(), IntegerGen(), LongGen(),
                FloatGen(), DoubleGen()]
INTEGRAL_GENS = [ByteGen(), ShortGen(), IntegerGen(), LongGen()]
FLOAT_GENS = [FloatGen(), DoubleGen()]
ORDERABLE_GENS = ALL_GENS


def gen_batch(gens: Sequence[Tuple[str, DataGen]], n: int,
              seed: int = 0) -> HostBatch:
    """data_gen.py gen_df analog -> HostBatch."""
    rng = np.random.default_rng(seed)
    schema = [(name, g.dtype) for name, g in gens]
    data = {name: g.gen(rng, n) for name, g in gens}
    return HostBatch.from_pydict(schema, data)


def unary_op_batch(gen: DataGen, n: int = 64, seed: int = 0) -> HostBatch:
    return gen_batch([("a", gen)], n, seed)


def binary_op_batch(gen_a: DataGen, gen_b: Optional[DataGen] = None,
                    n: int = 64, seed: int = 0) -> HostBatch:
    return gen_batch([("a", gen_a), ("b", gen_b or gen_a)], n, seed)


def gen_dict(gens: Sequence[Tuple[str, DataGen]], n: int, seed: int = 0):
    """Schema + python-dict form for TpuSession.create_dataframe."""
    rng = np.random.default_rng(seed)
    schema = [(name, g.dtype) for name, g in gens]
    data = {name: g.gen(rng, n) for name, g in gens}
    return schema, data
