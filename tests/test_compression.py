"""Compression codec SPI (TableCompressionCodec.scala:41 analog):
round-trips, registry, and the disk-spill integration."""

import os

import numpy as np
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.memory.compression import (
    CopyCodec, Lz4Codec, get_codec)


PAYLOADS = [
    b"",
    b"a",
    b"hello world " * 200,                       # highly compressible
    os.urandom(10_000),                          # incompressible
    bytes(np.arange(50_000, dtype=np.int32).view(np.uint8)),
    b"\x00" * 100_000,                           # long RLE run
    os.urandom(17) + b"abcd" * 5000 + os.urandom(23),
]


class TestCodecs:
    @pytest.mark.parametrize("name", ["lz4", "copy"])
    def test_round_trip(self, name):
        codec = get_codec(name)
        for p in PAYLOADS:
            c = codec.compress(p)
            assert codec.decompress(c, len(p)) == p

    def test_lz4_actually_compresses(self):
        codec = get_codec("lz4")
        if not isinstance(codec, Lz4Codec):
            pytest.skip("native lz4 unavailable")
        p = b"spark rapids tpu " * 4096
        c = codec.compress(p)
        assert len(c) < len(p) // 4

    def test_registry(self):
        assert get_codec("none") is None
        assert get_codec("") is None
        assert isinstance(get_codec("copy"), CopyCodec)
        with pytest.raises(ValueError):
            get_codec("zstd-nope")

    def test_lz4_rejects_corrupt(self):
        codec = get_codec("lz4")
        if not isinstance(codec, Lz4Codec):
            pytest.skip("native lz4 unavailable")
        good = codec.compress(b"x" * 1000)
        with pytest.raises(OSError):
            codec.decompress(good[: len(good) // 2], 1000)


class TestSpillIntegration:
    def _catalog(self, tmp_path, codec):
        from spark_rapids_tpu.memory.stores import BufferCatalog
        return BufferCatalog(device_budget_bytes=1 << 14,
                             host_budget_bytes=1 << 14,
                             spill_dir=str(tmp_path),
                             compression_codec=codec)

    def _batch(self, n=2048, fill=7):
        from spark_rapids_tpu.columnar.host import (
            HostBatch, host_to_device)
        hb = HostBatch.from_pydict(
            [("a", srt.INT64)], {"a": [fill] * n})
        return host_to_device(hb)

    @pytest.mark.parametrize("codec", ["lz4", "copy", "none"])
    def test_disk_round_trip(self, tmp_path, codec):
        cat = self._catalog(tmp_path, codec)
        ids = [cat.add_batch(self._batch(fill=i)) for i in range(6)]
        # Tiny budgets force the earliest entries down to disk.
        assert cat.metrics["spill_to_disk"] > 0
        from spark_rapids_tpu.columnar.host import device_to_host
        for i, bid in enumerate(ids):
            got = device_to_host(cat.acquire_batch(bid))
            assert got.columns[0].to_list() == [i] * 2048
            cat.release(bid)
        cat.close()

    def test_lz4_shrinks_spilled_bytes(self, tmp_path):
        cat = self._catalog(tmp_path, "lz4")
        for i in range(6):
            cat.add_batch(self._batch(fill=i))
        m = cat.metrics
        assert m["spill_to_disk"] > 0
        assert m["disk_bytes_stored"] < m["disk_bytes_raw"] // 2
        cat.close()
