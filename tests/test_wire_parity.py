"""Wire-codec transport transparency (ISSUE 8 acceptance): every codec
mode is lossless and the decode is gathers/bitcasts/exact-int-arith
only, so `wire.codec=plain`, `v1` and `v2` must produce BIT-IDENTICAL
results across the 11-query bench suite — same engine, same kernels,
only the upload encoding differs.

Fast tier runs the cheap scans + repartition; the CI wire matrix entry
(SRT_WIRE_CODEC=plain over the whole tier-1 suite) and the chaos run of
this file (no slow filter) cover the join/window-heavy remainder.
"""

import pytest

from spark_rapids_tpu.api.dataframe import TpuSession


def _session(codec: str):
    s = TpuSession()
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.wire.codec", codec)
    # Cold uploads every run: a scan-cache hit would serve batches that
    # never crossed the codec under test.
    s.set("spark.rapids.sql.format.scanCache.maxBytes", 0)
    return s


def _tpch_dir(tmp_path_factory):
    from spark_rapids_tpu.benchmarks import tpch
    d = getattr(_tpch_dir, "_dir", None)
    if d is None:
        d = str(tmp_path_factory.mktemp("wire_tpch"))
        tpch.generate(d, scale=0.003, files_per_table=3, seed=7)
        _tpch_dir._dir = d
    return d


def _suites_dir(tmp_path_factory):
    from spark_rapids_tpu.benchmarks import suites
    d = getattr(_suites_dir, "_dir", None)
    if d is None:
        d = str(tmp_path_factory.mktemp("wire_suites"))
        suites.generate(d, scale=0.01, files_per_table=2)
        _suites_dir._dir = d
    return d


# The 11-query suite: the 7 BASELINE target shapes + 4 coverage queries
# (two extra TPC-H joins, a TPC-DS-like and a TPCxBB-like).
_TPCH = ["q1", "q6",
         pytest.param("q3", marks=pytest.mark.slow),
         pytest.param("q5", marks=pytest.mark.slow),
         pytest.param("q12", marks=pytest.mark.slow),
         pytest.param("q14", marks=pytest.mark.slow)]
_SUITES = ["repart",
           pytest.param("q67", marks=pytest.mark.slow),
           pytest.param("xbb_q5", marks=pytest.mark.slow),
           pytest.param("ds_q3", marks=pytest.mark.slow),
           pytest.param("xbb_q12", marks=pytest.mark.slow)]


@pytest.mark.parametrize("qname", _TPCH)
def test_tpch_plain_vs_v2_bit_identical(qname, tmp_path_factory):
    from spark_rapids_tpu.benchmarks import tpch
    d = _tpch_dir(tmp_path_factory)
    v2 = tpch.QUERIES[qname](_session("v2"), d).collect()
    plain = tpch.QUERIES[qname](_session("plain"), d).collect()
    assert plain == v2


@pytest.mark.parametrize("qname", _SUITES)
def test_suites_plain_vs_v2_bit_identical(qname, tmp_path_factory):
    from spark_rapids_tpu.benchmarks import suites
    d = _suites_dir(tmp_path_factory)
    v2 = suites.QUERIES[qname](_session("v2"), d).collect()
    plain = suites.QUERIES[qname](_session("plain"), d).collect()
    assert plain == v2


def test_v1_matches_v2(tmp_path_factory):
    from spark_rapids_tpu.benchmarks import tpch
    d = _tpch_dir(tmp_path_factory)
    assert tpch.QUERIES["q1"](_session("v1"), d).collect() \
        == tpch.QUERIES["q1"](_session("v2"), d).collect()


def test_unknown_codec_rejected():
    from spark_rapids_tpu.columnar import wire
    from spark_rapids_tpu.config import TpuConf
    with pytest.raises(ValueError):
        wire.maybe_configure(TpuConf(
            {"spark.rapids.sql.wire.codec": "zstd"}))
    wire.maybe_configure(TpuConf())     # restore default
