"""DataFrame API + plan-rewrite layer tests.

Covers the tag->convert lifecycle: kill-switch fallbacks, incompat gating,
explain report, test mode, and end-to-end query shapes through the planner
(the SparkQueryCompareTestSuite style, now at the API level: collect() on
the device plan vs collect_host() on the host oracle engine).
"""

import math
import os

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.api import (
    TpuSession, agg_avg, agg_count, agg_max, agg_min, agg_sum, col, upper,
    when)
from spark_rapids_tpu.plan.logical import lit_col

from harness import assert_rows_equal


@pytest.fixture
def session():
    # Float aggs enabled for tests (results compared approx).
    return TpuSession({
        "spark.rapids.sql.variableFloatAgg.enabled": True,
        "spark.rapids.sql.incompatibleOps.enabled": True,
    })


SCHEMA = [("k", dt.STRING), ("v", dt.INT32), ("x", dt.FLOAT64)]
DATA = {
    "k": ["a", "b", "a", None, "b", "a", "c", None],
    "v": [1, 2, 3, 4, None, 6, 7, 8],
    "x": [1.0, 2.5, float("nan"), 4.0, 5.0, None, 7.5, 8.0],
}


def dual_collect(df, approx_float=False, sort_result=True):
    dev = df.collect()
    host = df.collect_host()
    if sort_result:
        keyf = lambda r: tuple((v is None, str(v)) for v in r)
        dev, host = sorted(dev, key=keyf), sorted(host, key=keyf)
    assert_rows_equal(dev, host, approx_float, "device vs host engine")
    return dev


class TestDataFrameBasics:
    def test_filter_select(self, session):
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        out = dual_collect(
            df.filter(col("v") > 3).select("k", (col("v") * 10).alias("v10")))
        assert sorted(out, key=str) == sorted(
            [(None, 40), ("a", 60), ("c", 70), (None, 80)], key=str)

    def test_with_column_case_when(self, session):
        df = session.create_dataframe(DATA, SCHEMA)
        df = df.with_column(
            "size", when(col("v") < 3, "small").otherwise("big"))
        out = dual_collect(df.select("v", "size"))
        assert ("small" in {r[1] for r in out} and
                "big" in {r[1] for r in out})

    def test_group_by_agg(self, session):
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        out = dual_collect(
            df.group_by("k").agg(
                agg_count().alias("n"),
                agg_sum(col("v")).alias("sv"),
                agg_avg(col("x")).alias("ax")), approx_float=True)
        asmap = {r[0]: r[1:] for r in out}
        assert asmap["a"][0] == 3 and asmap["a"][1] == 10
        assert asmap[None][0] == 2 and asmap[None][1] == 12

    def test_global_agg(self, session):
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=3)
        out = dual_collect(df.agg(agg_count().alias("n"),
                                  agg_min(col("v")).alias("mn"),
                                  agg_max(col("v")).alias("mx")))
        assert out == [(8, 1, 8)]

    def test_order_by_limit(self, session):
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        out = df.order_by(col("v").desc()).limit(3).collect()
        assert [r[1] for r in out] == [8, 7, 6]

    def test_join_api(self, session):
        orders = session.create_dataframe(
            {"ok": [1, 2, 3], "cust": [10, 20, 10]},
            [("ok", dt.INT32), ("cust", dt.INT32)])
        cust = session.create_dataframe(
            {"ck": [10, 30], "name": ["alice", "carol"]},
            [("ck", dt.INT32), ("name", dt.STRING)])
        out = dual_collect(orders.join_on(cust, ["cust"], ["ck"], "left"))
        assert sorted(out, key=str) == sorted(
            [(1, 10, 10, "alice"), (3, 10, 10, "alice"),
             (2, 20, None, None)], key=str)

    def test_union_repartition(self, session):
        df = session.create_dataframe(DATA, SCHEMA)
        u = df.union(df).repartition(3, "k")
        out = dual_collect(u)
        assert len(out) == 16

    def test_range(self, session):
        out = dual_collect(
            session.range(0, 30, 3, num_partitions=2), sort_result=False)
        assert sorted(r[0] for r in out) == list(range(0, 30, 3))

    def test_shuffled_join_strategy(self, session):
        left = session.create_dataframe(
            {"k": [1, 2, 2, 3], "v": [10, 20, 21, 30]},
            [("k", dt.INT32), ("v", dt.INT32)], num_partitions=2)
        right = session.create_dataframe(
            {"k2": [2, 3, 4], "w": [200, 300, 400]},
            [("k2", dt.INT32), ("w", dt.INT32)])
        out = dual_collect(left.join_on(right, ["k"], ["k2"], "full",
                                        strategy="shuffle"))
        assert sorted(out, key=str) == sorted(
            [(1, 10, None, None), (2, 20, 2, 200), (2, 21, 2, 200),
             (3, 30, 3, 300), (None, None, 4, 400)], key=str)


class TestDistinctAggregates:
    """DISTINCT aggregates via the partial-merge mode combos
    (aggregate.scala:305 distinct handling)."""

    def test_count_distinct_grouped(self, session):
        from spark_rapids_tpu.api import agg_count_distinct
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=3)
        out = dual_collect(df.group_by("k").agg(
            agg_count_distinct(col("v")).alias("dv")))
        asmap = dict(out)
        # a: v = 1,3,6 -> 3 distinct; None: 4,8 -> 2; b: 2,None -> 1
        assert asmap["a"] == 3 and asmap[None] == 2 and asmap["b"] == 1

    def test_count_distinct_with_duplicates(self, session):
        from spark_rapids_tpu.api import agg_count_distinct, agg_sum_distinct
        data = {"k": ["a", "a", "a", "b", "b", "b", "b"],
                "v": [1, 1, 2, 5, 5, 5, None]}
        schema = [("k", dt.STRING), ("v", dt.INT32)]
        df = session.create_dataframe(data, schema, num_partitions=2)
        out = dual_collect(df.group_by("k").agg(
            agg_count_distinct(col("v")).alias("dc"),
            agg_sum_distinct(col("v")).alias("ds")))
        asmap = {r[0]: r[1:] for r in out}
        assert asmap["a"] == (2, 3)     # {1,2}
        assert asmap["b"] == (1, 5)     # {5}

    def test_distinct_mixed_with_plain_aggs(self, session):
        from spark_rapids_tpu.api import agg_count_distinct
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        out = dual_collect(df.group_by("k").agg(
            agg_count().alias("n"),
            agg_count_distinct(col("v")).alias("dv"),
            agg_sum(col("v")).alias("sv")))
        asmap = {r[0]: r[1:] for r in out}
        assert asmap["a"] == (3, 3, 10)
        assert asmap["b"] == (2, 1, 2)
        assert asmap[None] == (2, 2, 12)

    def test_global_count_distinct(self, session):
        from spark_rapids_tpu.api import agg_count_distinct
        data = {"v": [3, 1, 3, None, 1, 3]}
        df = session.create_dataframe(data, [("v", dt.INT32)],
                                      num_partitions=3)
        out = dual_collect(df.agg(agg_count_distinct(col("v")).alias("d")))
        assert out == [(2,)]

    def test_avg_distinct(self, session):
        from spark_rapids_tpu.api import agg_avg_distinct
        data = {"k": ["a", "a", "a", "b"], "x": [2.0, 2.0, 4.0, 10.0]}
        schema = [("k", dt.STRING), ("x", dt.FLOAT64)]
        df = session.create_dataframe(data, schema, num_partitions=2)
        out = dual_collect(df.group_by("k").agg(
            agg_avg_distinct(col("x")).alias("ax")), approx_float=True)
        asmap = dict(out)
        assert asmap["a"] == 3.0 and asmap["b"] == 10.0

    def test_multiple_distinct_same_input_ok(self, session):
        from spark_rapids_tpu.api import agg_count_distinct, agg_sum_distinct
        df = session.create_dataframe(DATA, SCHEMA)
        out = dual_collect(df.group_by("k").agg(
            agg_count_distinct(col("v")).alias("c"),
            agg_sum_distinct(col("v")).alias("s")))
        asmap = {r[0]: r[1:] for r in out}
        assert asmap["a"] == (3, 10)

    def test_multiple_distinct_different_inputs_rejected(self, session):
        from spark_rapids_tpu.api import agg_count_distinct
        from spark_rapids_tpu.plan.logical import ResolutionError
        df = session.create_dataframe(DATA, SCHEMA)
        q = df.group_by("k").agg(
            agg_count_distinct(col("v")).alias("a"),
            agg_count_distinct(col("x")).alias("b"))
        with pytest.raises(ResolutionError):
            q.collect()

    def test_distinct_input_check_sees_constructor_args(self, session):
        # round(v, 1) vs round(v, 2) must be rejected even though both
        # pretty-print as Round(v) — the structural key keeps the scale.
        from spark_rapids_tpu.api import (agg_count_distinct,
                                          agg_sum_distinct, round_col)
        from spark_rapids_tpu.plan.logical import ResolutionError
        df = session.create_dataframe(DATA, SCHEMA)
        q = df.group_by("k").agg(
            agg_sum_distinct(round_col(col("x"), 1)).alias("a"),
            agg_count_distinct(round_col(col("x"), 2)).alias("b"))
        with pytest.raises(ResolutionError):
            q.collect()


class TestPlanRewrite:
    def test_exec_kill_switch_falls_back(self):
        s = TpuSession({"spark.rapids.sql.exec.LogicalFilter": False})
        df = s.create_dataframe(DATA, SCHEMA).filter(col("v") > 3)
        phys = df._physical()
        assert "LogicalFilter" in phys.host_fallback_nodes()
        # Still correct via the host island:
        assert len(phys.collect()) == 4

    def test_expression_kill_switch(self):
        s = TpuSession({"spark.rapids.sql.expression.gt": False})
        df = s.create_dataframe(DATA, SCHEMA).filter(col("v") > 3)
        report = df._physical().explain()
        assert "expression gt disabled" in report
        assert len(df.collect()) == 4

    def test_incompat_upper_fallback_by_default(self):
        s = TpuSession()
        df = s.create_dataframe(DATA, SCHEMA).select(
            upper(col("k")).alias("K"))
        phys = df._physical()
        assert "LogicalProject" in phys.host_fallback_nodes()
        s2 = TpuSession({"spark.rapids.sql.incompatibleOps.enabled": True})
        df2 = s2.create_dataframe(DATA, SCHEMA).select(
            upper(col("k")).alias("K"))
        assert df2._physical().host_fallback_nodes() == []
        assert sorted(df.collect(), key=str) == \
            sorted(df2.collect(), key=str)

    def test_float_agg_gate(self):
        s = TpuSession()
        df = s.create_dataframe(DATA, SCHEMA).group_by("k").agg(
            agg_sum(col("x")).alias("sx"))
        phys = df._physical()
        assert any("vary with evaluation order" in r
                   for r in phys.meta.reasons)

    def test_test_mode_fails_on_host_node(self):
        s = TpuSession({
            "spark.rapids.sql.exec.LogicalFilter": False,
            "spark.rapids.sql.test.enabled": True,
        })
        df = s.create_dataframe(DATA, SCHEMA).filter(col("v") > 3)
        with pytest.raises(AssertionError, match="execute on host"):
            df._physical()

    def test_test_mode_allowlist(self):
        s = TpuSession({
            "spark.rapids.sql.exec.LogicalFilter": False,
            "spark.rapids.sql.test.enabled": True,
            "spark.rapids.sql.test.allowedNonTpu": "LogicalFilter",
        })
        df = s.create_dataframe(DATA, SCHEMA).filter(col("v") > 3)
        df._physical()   # no raise

    def test_explain_report(self, session):
        df = session.create_dataframe(DATA, SCHEMA) \
            .filter(col("v") > 3).group_by("k").agg(
                agg_count().alias("n"))
        report = df._physical().explain()
        assert "*Exec <LogicalAggregate>" in report
        assert "*Exec <LogicalFilter>" in report
        assert "*Exec <InMemoryScan>" in report

    def test_sql_enabled_false_runs_all_host(self):
        s = TpuSession({"spark.rapids.sql.enabled": False})
        df = s.create_dataframe(DATA, SCHEMA).filter(col("v") > 3)
        phys = df._physical()
        assert not phys.root_on_device
        assert len(phys.collect()) == 4


class TestIO:
    def test_parquet_roundtrip(self, session, tmp_path):
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        out = str(tmp_path / "t.parquet")
        df.write.mode("overwrite").parquet(out)
        back = session.read.parquet(*sorted(
            str(p) for p in (tmp_path / "t.parquet").glob("part-*")))
        got = dual_collect(back)
        exp = sorted(df.collect(), key=lambda r: tuple(
            (v is None, str(v)) for v in r))
        assert_rows_equal(got, exp, msg="parquet roundtrip")

    def test_parquet_reader_types(self, session, tmp_path):
        df = session.create_dataframe(DATA, SCHEMA)
        out = str(tmp_path / "t2")
        df.write.mode("overwrite").parquet(out)
        paths = sorted(str(p) for p in (tmp_path / "t2").glob("part-*"))
        for rt in ("PERFILE", "MULTITHREADED", "COALESCING"):
            s = TpuSession({
                "spark.rapids.sql.format.parquet.reader.type": rt,
                "spark.rapids.sql.incompatibleOps.enabled": True,
            })
            back = s.read.parquet(*paths)
            assert len(back.collect()) == 8

    def test_device_scan_cache_hits_and_invalidates(self, session,
                                                    tmp_path):
        from spark_rapids_tpu.io.scan import DEVICE_SCAN_CACHE
        from spark_rapids_tpu.ops.base import ExecContext
        # Asserts DEVICE scan-cache hits; the cost model would
        # (correctly) host-place this tiny scan.
        session.set("spark.rapids.sql.cost.enabled", False)
        DEVICE_SCAN_CACHE.clear()
        df = session.create_dataframe(DATA, SCHEMA, num_partitions=2)
        out = str(tmp_path / "tc")
        df.write.mode("overwrite").parquet(out)
        paths = sorted(str(p) for p in (tmp_path / "tc").glob("part-*"))
        back = session.read.parquet(*paths)
        first = sorted(map(repr, back.collect()))
        phys = back._physical()
        ctx = ExecContext(phys.conf)
        second = sorted(map(repr, phys.collect(ctx)))
        assert second == first
        hits = sum(m.values.get("scanCacheHits", 0)
                   for m in ctx.metrics.values())
        assert hits > 0, "second scan should be served from device cache"
        ctx.close()
        # Rewriting the file must invalidate (mtime/size key).
        session.create_dataframe(
            {k: list(reversed(v)) for k, v in DATA.items()}, SCHEMA,
            num_partitions=2).write.mode("overwrite").parquet(out)
        paths2 = sorted(str(p) for p in (tmp_path / "tc").glob("part-*"))
        again = session.read.parquet(*paths2)
        assert sorted(map(repr, again.collect())) == first

    def test_scan_cache_disabled_by_zero_budget(self, tmp_path):
        from spark_rapids_tpu.io.scan import DEVICE_SCAN_CACHE
        from spark_rapids_tpu.ops.base import ExecContext
        DEVICE_SCAN_CACHE.clear()
        s = TpuSession({
            "spark.rapids.sql.format.scanCache.maxBytes": 0,
            "spark.rapids.sql.incompatibleOps.enabled": True,
        })
        df = s.create_dataframe(DATA, SCHEMA)
        out = str(tmp_path / "tnc")
        df.write.mode("overwrite").parquet(out)
        back = s.read.parquet(*sorted(
            str(p) for p in (tmp_path / "tnc").glob("part-*")))
        back.collect()
        phys = back._physical()
        ctx = ExecContext(phys.conf)
        phys.collect(ctx)
        hits = sum(m.values.get("scanCacheHits", 0)
                   for m in ctx.metrics.values())
        assert hits == 0
        ctx.close()

    def test_csv_roundtrip(self, session, tmp_path):
        schema = [("a", dt.INT64), ("b", dt.STRING)]
        df = session.create_dataframe(
            {"a": [1, 2, 3], "b": ["x", "y", "z"]}, schema)
        out = str(tmp_path / "t.csv")
        df.write.mode("overwrite").csv(out)
        back = session.read.csv(*sorted(
            str(p) for p in (tmp_path / "t.csv").glob("part-*")))
        assert sorted(back.collect()) == [(1, "x"), (2, "y"), (3, "z")]

    def test_orc_roundtrip(self, session, tmp_path):
        schema = [("a", dt.INT64), ("x", dt.FLOAT64)]
        df = session.create_dataframe(
            {"a": [1, 2, None], "x": [1.5, None, 3.5]}, schema)
        out = str(tmp_path / "t.orc")
        df.write.mode("overwrite").orc(out)
        back = session.read.orc(*sorted(
            str(p) for p in (tmp_path / "t.orc").glob("part-*")))
        got = dual_collect(back)
        assert got == sorted(
            [(1, 1.5), (2, None), (None, 3.5)],
            key=lambda r: tuple((v is None, str(v)) for v in r))

    def test_q1_like_from_parquet(self, session, tmp_path):
        rng = np.random.default_rng(7)
        n = 5000
        df = session.create_dataframe(
            {"flag": rng.choice(["A", "N", "R"], n).tolist(),
             "qty": rng.integers(1, 50, n).tolist(),
             "price": (rng.random(n) * 100).tolist()},
            [("flag", dt.STRING), ("qty", dt.INT64),
             ("price", dt.FLOAT64)], num_partitions=2)
        path = str(tmp_path / "lineitem")
        df.write.mode("overwrite").parquet(path)
        files = sorted(str(p) for p in
                       (tmp_path / "lineitem").glob("part-*"))
        q = (session.read.parquet(*files)
             .filter(col("qty") <= 45)
             .group_by("flag")
             .agg(agg_sum(col("qty")).alias("sum_qty"),
                  agg_avg(col("price")).alias("avg_price"),
                  agg_count().alias("n"))
             .order_by("flag"))
        out = dual_collect(q, approx_float=True, sort_result=False)
        assert [r[0] for r in out] == ["A", "N", "R"]


class TestOrcPushdown:
    """ORC stripe pruning via the engine's first-contact stats index
    (OrcFilters.scala:206 analog — pyarrow exposes no ORC column stats,
    so the engine builds its own)."""

    def test_orc_stripe_pruning(self, tmp_path):
        import pyarrow as pa
        import pyarrow.orc as paorc
        import numpy as np
        from spark_rapids_tpu.api.dataframe import TpuSession
        from spark_rapids_tpu.plan.logical import col
        # Two files with disjoint ranges -> the filter can prune one.
        p1 = str(tmp_path / "a.orc")
        p2 = str(tmp_path / "b.orc")
        paorc.write_table(pa.table(
            {"x": np.arange(0, 1000, dtype=np.int64)}), p1)
        paorc.write_table(pa.table(
            {"x": np.arange(5000, 6000, dtype=np.int64)}), p2)
        s = TpuSession()
        # Asserts the DEVICE scan's stripe-pruning counters; the cost
        # model would (correctly) host-place this tiny ORC scan.
        s.set("spark.rapids.sql.cost.enabled", False)
        df = s.read.orc(p1, p2).filter(col("x") >= 5500)
        got = sorted(r[0] for r in df.collect())
        assert got == list(range(5500, 6000))
        # Second run hits the stats cache and actually prunes: the
        # skipped-unit metric must show at least one skipped stripe.
        df2 = s.read.orc(p1, p2).filter(col("x") >= 5500)
        df2.collect()
        m = df2._physical().last_ctx.metrics
        scans = [v.values for k, v in m.items() if "FileScan" in k]
        assert any(v.get("numSkippedRowGroups", 0) >= 1 for v in scans)
