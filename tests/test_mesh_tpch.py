"""A REAL TPC-H query through the mesh collective path (VERDICT r3 item 5):
q3 (two joins + aggregate + top-k) planned with
``spark.rapids.sql.mesh.enabled=true`` executes its hash exchanges as
``jax.lax.all_to_all`` collectives over the 8-virtual-CPU-device mesh
(conftest) and matches the single-device plan bit-for-bit."""

import time

import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpch_mesh")
    tpch.generate(str(d), scale=0.004, files_per_table=4)
    return str(d)


def _session(mesh: bool) -> TpuSession:
    s = TpuSession()
    s.set("spark.rapids.sql.mesh.enabled", mesh)
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    # Shuffle joins force exchanges on both sides so the mesh path is
    # actually exercised (auto would broadcast the dimension tables).
    return s


def _q3(s: TpuSession, data_dir: str):
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col

    def read(table):
        return s.read.parquet(*tpch._paths(data_dir, table))

    cust = read("customer") \
        .filter(col("c_mktsegment") == lit_col("BUILDING")) \
        .select("c_custkey")
    orders = read("orders") \
        .filter(col("o_orderdate") < lit_col(tpch.days("1995-03-15"))) \
        .select("o_orderkey", "o_custkey", "o_orderdate", "o_shippriority")
    li = read("lineitem") \
        .filter(col("l_shipdate") > lit_col(tpch.days("1995-03-15"))) \
        .select("l_orderkey", "l_extendedprice", "l_discount")
    co = orders.join_on(cust, ["o_custkey"], ["c_custkey"],
                        strategy="shuffle")
    j = li.join_on(co, ["l_orderkey"], ["o_orderkey"], strategy="shuffle")
    return j.group_by("l_orderkey", "o_orderdate", "o_shippriority").agg(
        agg_sum(col("l_extendedprice") * (1.0 - col("l_discount")))
        .alias("revenue")
    ).order_by(col("revenue").desc(), col("o_orderdate").asc()).limit(10)


def test_q3_through_mesh_collectives(data_dir):
    t0 = time.perf_counter()
    mesh_rows = _q3(_session(True), data_dir).collect()
    mesh_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    single_rows = _q3(_session(False), data_dir).collect()
    single_s = time.perf_counter() - t0
    pandas_rows = tpch.pandas_query("q3", data_dir)
    # Epsilon compare: the runs legitimately order f64 partial sums
    # differently (variableFloatAgg is enabled; AQE partition coalescing
    # changes the merge grouping).
    assert tpch.rows_close(mesh_rows, single_rows)
    assert tpch.check_result("q3", mesh_rows, pandas_rows)
    # Timing recorded for the log (no assertion: virtual devices share
    # one CPU, so mesh wall time only validates, not accelerates).
    print(f"q3 mesh={mesh_s:.2f}s single={single_s:.2f}s "
          f"rows={len(mesh_rows)}")


def test_q3_mesh_plan_contains_collective_exchanges(data_dir):
    from spark_rapids_tpu.parallel.mesh_exchange import MeshExchangeExec
    phys = _q3(_session(True), data_dir)._physical()
    found = []

    def walk(node):
        if isinstance(node, MeshExchangeExec):
            found.append(node)
        for c in node.children:
            walk(c)

    walk(phys.root)
    # Both join sides x 2 joins + the aggregate exchange.
    assert len(found) >= 4


@pytest.mark.parametrize("qn", ["q4", "q12"])
def test_more_queries_through_mesh_collectives(qn, data_dir):
    """Semi-join (q4) and join+conditional-agg (q12) shapes through the
    all_to_all mesh path match the single-device plan and the pandas
    oracle."""
    mesh_rows = tpch.QUERIES[qn](_session(True), data_dir).collect()
    single_rows = tpch.QUERIES[qn](_session(False), data_dir).collect()
    pandas_rows = tpch.pandas_query(qn, data_dir)
    assert tpch.rows_close(sorted(mesh_rows), sorted(single_rows))
    assert tpch.check_result(qn, mesh_rows, pandas_rows)
