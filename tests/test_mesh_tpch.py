"""All 22 TPC-H queries through every shuffle transport (ISSUE 6; was
q3/q4/q12 only).

Each query runs three ways on the 8-virtual-CPU-device mesh (conftest):

- ``inprocess`` — the single-process materialized exchange (baseline);
- ``hostfile`` — shards spool through the cross-process host-file
  transport; the numpy round trip is bit-exact and the fetch order is
  deterministic, so results must equal the baseline TO THE BIT;
- ``mesh`` — hash exchanges run as ``jax.lax.all_to_all`` collectives;
  float partial sums legitimately merge in a different order
  (variableFloatAgg is enabled), so the compare is epsilon-aware
  (``rows_close``), with the pandas oracle as the correctness anchor.
"""

import time

import pytest

from spark_rapids_tpu.api.dataframe import TpuSession
from spark_rapids_tpu.benchmarks import tpch


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpch_mesh")
    tpch.generate(str(d), scale=0.004, files_per_table=4)
    return str(d)


@pytest.fixture(scope="module")
def spool_dir(tmp_path_factory):
    return str(tmp_path_factory.mktemp("tpch_mesh_spool"))


def _session(transport: str, spool: str = "") -> TpuSession:
    s = TpuSession()
    s.set("spark.rapids.sql.shuffle.transport", transport)
    s.set("spark.rapids.sql.variableFloatAgg.enabled", True)
    s.set("spark.rapids.sql.hasNans", False)
    # Transport parity needs the DEVICE exchange paths; the cost model
    # would host-place these mini-scale queries (correctly) and bypass
    # the transports under test.
    s.set("spark.rapids.sql.cost.enabled", False)
    if spool:
        s.set("spark.rapids.sql.shuffle.transport.hostfile.dir", spool)
    # Shuffle joins force exchanges on both sides so the transport under
    # test is actually exercised (auto would broadcast the dimension
    # tables).
    return s


def _q3(s: TpuSession, data_dir: str):
    from spark_rapids_tpu.plan.logical import agg_sum, col, lit_col

    def read(table):
        return s.read.parquet(*tpch._paths(data_dir, table))

    cust = read("customer") \
        .filter(col("c_mktsegment") == lit_col("BUILDING")) \
        .select("c_custkey")
    orders = read("orders") \
        .filter(col("o_orderdate") < lit_col(tpch.days("1995-03-15"))) \
        .select("o_orderkey", "o_custkey", "o_orderdate", "o_shippriority")
    li = read("lineitem") \
        .filter(col("l_shipdate") > lit_col(tpch.days("1995-03-15"))) \
        .select("l_orderkey", "l_extendedprice", "l_discount")
    co = orders.join_on(cust, ["o_custkey"], ["c_custkey"],
                        strategy="shuffle")
    j = li.join_on(co, ["l_orderkey"], ["o_orderkey"], strategy="shuffle")
    return j.group_by("l_orderkey", "o_orderdate", "o_shippriority").agg(
        agg_sum(col("l_extendedprice") * (1.0 - col("l_discount")))
        .alias("revenue")
    ).order_by(col("revenue").desc(), col("o_orderdate").asc()).limit(10)


def test_q3_through_mesh_collectives(data_dir):
    t0 = time.perf_counter()
    mesh_rows = _q3(_session("mesh"), data_dir).collect()
    mesh_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    single_rows = _q3(_session("inprocess"), data_dir).collect()
    single_s = time.perf_counter() - t0
    pandas_rows = tpch.pandas_query("q3", data_dir)
    # Epsilon compare: the runs legitimately order f64 partial sums
    # differently (variableFloatAgg is enabled; AQE partition coalescing
    # changes the merge grouping).
    assert tpch.rows_close(mesh_rows, single_rows)
    assert tpch.check_result("q3", mesh_rows, pandas_rows)
    # Timing recorded for the log (no assertion: virtual devices share
    # one CPU, so mesh wall time only validates, not accelerates).
    print(f"q3 mesh={mesh_s:.2f}s single={single_s:.2f}s "
          f"rows={len(mesh_rows)}")


def test_q3_mesh_plan_contains_collective_exchanges(data_dir):
    from spark_rapids_tpu.parallel.mesh_exchange import MeshExchangeExec
    phys = _q3(_session("mesh"), data_dir)._physical()
    found = []

    def walk(node):
        if isinstance(node, MeshExchangeExec):
            found.append(node)
        for c in node.children:
            walk(c)

    walk(phys.root)
    # Both join sides x 2 joins + the aggregate exchange.
    assert len(found) >= 4


def test_mesh_folds_arbitrary_partition_counts(data_dir):
    """Partition count != mesh size folds onto the mesh (counter
    meshPartitionFolds) instead of degrading to the single-process path
    (the old meshCollectiveSkipped), bit-identical results included."""
    from spark_rapids_tpu import faults
    want = None
    for parts in (16, 5):
        faults.reset_counters()
        s = _session("mesh")
        s.set("spark.rapids.sql.shuffle.partitions", parts)
        got = tpch.QUERIES["q4"](s, data_dir).collect()
        c = faults.counters()
        assert c.get("meshPartitionFolds", 0) >= 1, \
            f"parts={parts}: fold pass never ran"
        assert not c.get("meshCollectiveSkipped"), \
            f"parts={parts}: collective degraded instead of folding"
        if want is None:
            want = tpch.QUERIES["q4"](_session("inprocess"),
                                      data_dir).collect()
        assert tpch.rows_close(sorted(got), sorted(want))


# Tier-1 runs a representative fast subset inline; the full 22-query
# sweep rides the CI transport matrix (slow marker — pyproject.toml).
_FAST = {"q1", "q3", "q4", "q6", "q12"}


@pytest.mark.parametrize(
    "qn",
    [q if q in _FAST else pytest.param(q, marks=pytest.mark.slow)
     for q in sorted(tpch.QUERIES, key=lambda q: int(q[1:]))])
def test_query_through_all_transports(qn, data_dir, spool_dir):
    """Every TPC-H query through all three shuffle transports: hostfile
    must match the in-process baseline bit-for-bit, the mesh collective
    epsilon-close, and the baseline must match the pandas oracle."""
    single_rows = tpch.QUERIES[qn](_session("inprocess"),
                                   data_dir).collect()
    hostfile_rows = tpch.QUERIES[qn](_session("hostfile", spool_dir),
                                     data_dir).collect()
    assert hostfile_rows == single_rows, (
        f"{qn}: hostfile transport diverged from the in-process "
        f"exchange\n  got[:3]={hostfile_rows[:3]}\n"
        f"  want[:3]={single_rows[:3]}")
    mesh_rows = tpch.QUERIES[qn](_session("mesh"), data_dir).collect()
    assert tpch.rows_close(sorted(mesh_rows), sorted(single_rows)), (
        f"{qn}: mesh collective diverged from the in-process exchange")
    pandas_rows = tpch.pandas_query(qn, data_dir)
    assert tpch.check_result(qn, single_rows, pandas_rows), (
        f"{qn}: device result diverges from pandas oracle")
